"""Continuous-batching serving engine (Orca-style step-boundary scheduling).

:class:`ServingEngine` is the online front door over a decode-capable model
(anything exposing ``serving_step`` / ``_gen_params`` — ``TransformerLM`` in
the zoo): callers ``submit()`` token prompts from any thread; one scheduler
thread runs the slot batch.

The data path, end to end:

1. **Admission** — ``submit()`` drops the request into a bounded queue
   (full → :exc:`QueueFullError`, the backpressure contract). A
   :class:`DeviceFeed` producer stages each prompt device-resident (padded
   to its 32-token bucket) so admission never pays a host→device transfer
   inside the decode loop; the scheduler drains it with the non-blocking
   ``poll()``.
2. **Prefill** — the prompt runs through a separate B=1 chunked program
   (``kv.build_prefill``, keyed per prompt bucket) producing the request's
   KV page plus its first token(s); the page is merged into a free slot row
   of the engine's static ``(L, 2, slots, H, TOT, D)`` cache. TTFT is
   prefill latency — a long prompt never stalls the in-flight slot batch.
3. **Decode** — ``kv.build_decode`` runs ``chunk`` greedy steps over ALL
   slots per dispatch; per-slot token/position/active/limit arrays are
   traced inputs, so requests retiring and joining between dispatches reuse
   the same compiled program (ONE trace per (slots, TOT bucket) — the
   compile-guard contract). Finished/cancelled/expired requests retire at
   chunk boundaries and their slots are immediately re-admissible.

Guardrails: every dispatch heartbeats the resilience watchdog on the
``serving`` source (arm with ``MXTPU_SERVING_STALL_S``), spans land in the
unified trace under ``serving/*``, and counters in
``profiler.get_serving_stats()``.

Live elasticity (ROADMAP item 4, ``docs/resilience.md``): ``drain()`` stops
admission, parks the scheduler at a chunk boundary, and freezes every
in-flight request — its KV page, next-token/position/limit slot state, and
handle — into a :class:`ServingHandoff`; ``adopt()`` on a fresh engine (same
model, survivor mesh) reinstalls the pages and resumes decoding the SAME
request handles bit-exactly, with zero drops. Queued-but-unprefilled
requests ride along and are re-staged on the adopting engine.

Knobs: ``MXTPU_SERVING_SLOTS`` (slot-batch capacity, default 4),
``MXTPU_SERVING_QUEUE`` (admission queue depth, default 16),
``MXTPU_SERVING_CHUNK`` (decode steps per dispatch, default 8),
``MXTPU_SERVING_PROGRAM_CACHE`` (LRU bound on the program caches).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from .. import profiler
from ..device_feed import DeviceFeed
from ..ndarray.ndarray import NDArray
from ..observability import tracer
from ..resilience.elastic import elastic_watchdog
from ..resilience.faults import fault_point
from ..resilience.watchdog import Watchdog, heartbeat
from ..step_cache import ProgramCache
from . import kv
from .api import (CANCELLED, DONE, EXPIRED, RUNNING, QueueFullError,
                  ServingRequest)

__all__ = ["ServingEngine", "ServingHandoff"]


@dataclass
class ServingHandoff:
    """Frozen in-flight serving state from :meth:`ServingEngine.drain`,
    consumable by :meth:`ServingEngine.adopt` on a fresh engine. Everything
    is host-resident (pages are numpy), so the handoff survives the source
    mesh disappearing entirely."""
    tot: int                                  # KV bucket length of each page
    entries: List[dict] = field(default_factory=list)   # per in-flight slot:
    #   req / page (L,2,1,H,tot,D np) / tok / p / limit / left
    pending: List[ServingRequest] = field(default_factory=list)  # admitted,
    #   never prefilled — re-staged verbatim by adopt()

    @property
    def in_flight(self) -> int:
        return len(self.entries) + len(self.pending)


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


class ServingEngine:
    """Online continuous-batching server over one decode-capable model.

    Greedy decoding only (the bit-exactness contract is argmax vs solo
    ``generate``); sampling requests belong on a per-request ``generate``
    path until the engine grows per-slot rng lanes."""

    def __init__(self, model, slots: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 chunk: Optional[int] = None,
                 stall_deadline_s: Optional[float] = None):
        self._model = model
        self.slots = slots if slots else _env_int("MXTPU_SERVING_SLOTS", 4)
        self.queue_depth = queue_depth if queue_depth \
            else _env_int("MXTPU_SERVING_QUEUE", 16)
        self.chunk = chunk if chunk else _env_int("MXTPU_SERVING_CHUNK", 8)
        if stall_deadline_s is None:
            raw = os.environ.get("MXTPU_SERVING_STALL_S", "")
            stall_deadline_s = float(raw) if raw else None
        self._stall_deadline_s = stall_deadline_s
        self._submit_q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._start_lock = threading.Lock()
        self._decode_fns = ProgramCache("serving_decode")
        self._prefill_fns = ProgramCache("serving_prefill")
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._feed: Optional[DeviceFeed] = None
        self._wd: Optional[Watchdog] = None
        self._error: Optional[BaseException] = None
        # slot state (scheduler-thread-owned; riders of the decode trace)
        self._params = None
        self._caches = None
        self._TOT: Optional[int] = None
        self._tok = np.zeros(self.slots, np.int32)
        self._p = np.zeros(self.slots, np.int32)
        self._limit = np.zeros(self.slots, np.int32)
        self._active = np.zeros(self.slots, bool)
        self._left = np.zeros(self.slots, np.int64)
        self._reqs: List[Optional[ServingRequest]] = [None] * self.slots

    # -- public surface ------------------------------------------------------
    def start(self) -> "ServingEngine":
        with self._start_lock:
            if self._thread is not None:
                return self
            self._materialize_params()
            profiler.record_serving("slots", self.slots)
            self._feed = DeviceFeed(self._staging_source(), depth=2)
            if self._stall_deadline_s:
                self._wd = Watchdog(deadline_s=self._stall_deadline_s,
                                    source="serving").start()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mxtpu-serving-scheduler")
            self._thread.start()
            self._started.set()
        return self

    def submit(self, prompt, max_new_tokens: int,
               deadline_s: Optional[float] = None) -> ServingRequest:
        """Enqueue one generation request; returns its handle immediately.
        Raises :exc:`QueueFullError` when the admission queue is at
        capacity (backpressure, not silent growth) and ``ValueError`` for
        requests the model can't hold."""
        if self._draining.is_set():
            raise RuntimeError(
                "ServingEngine is draining — submit to the adopting engine")
        if self._stop.is_set():
            raise RuntimeError("ServingEngine is stopped")
        req = ServingRequest(prompt, max_new_tokens, deadline_s)
        if req.total > self._model._max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + {req.max_new} new exceeds "
                f"max_len {self._model._max_len}")
        if self._thread is None:
            self.start()
        try:
            self._submit_q.put_nowait(req)
        except queue.Full:
            profiler.record_serving("rejected")
            tracer.instant("serving/reject", cat="serving",
                           args={"id": req.id})
            raise QueueFullError(
                f"admission queue full ({self.queue_depth}); request "
                f"{req.id} rejected") from None
        profiler.record_serving("submitted")
        profiler.record_serving("queue_depth_max", self._submit_q.qsize())
        return req

    def stats(self) -> dict:
        return profiler.get_serving_stats()

    def stop(self) -> None:
        """Stop the scheduler; queued and in-flight requests are finished
        as CANCELLED so no caller blocks forever."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
        if self._feed is not None:
            self._feed.close()
        if self._wd is not None:
            self._wd.stop()
        if self._error is not None:
            raise self._error

    def drain(self) -> ServingHandoff:
        """Zero-drop handoff, half one: stop admission (``submit`` raises),
        park the scheduler at its chunk boundary, and freeze every live
        request — KV page, slot cursors, handle — into a host-resident
        :class:`ServingHandoff` for :meth:`adopt` on a successor engine.
        No request is cancelled; callers blocked in ``result()`` simply keep
        waiting across the handoff. Runs under the ``elastic`` heartbeat
        source (``MXTPU_ELASTIC_STALL_S``) and the ``serving.drain`` fault
        seam; on any failure the normal cancel-everything sweep runs before
        the error propagates, so the no-caller-blocks-forever contract holds
        even when the handoff itself dies."""
        if self._thread is None:
            raise RuntimeError("ServingEngine is not started")
        with tracer.span("serving/drain", cat="serving"), elastic_watchdog():
            heartbeat("elastic")
            self._draining.set()      # submit() now raises
            self._stop.set()          # scheduler exits at the chunk boundary
            self._thread.join(timeout=60)
            if self._error is not None:
                raise self._error     # sweep already ran in the scheduler
            try:
                fault_point("serving.drain")
                now = time.monotonic()
                entries: List[dict] = []
                for slot in np.flatnonzero(self._active):
                    slot = int(slot)
                    req = self._reqs[slot]
                    if req._cancelled():
                        self._retire(slot, CANCELLED, now)
                        continue
                    if req._expired(now):
                        self._retire(slot, EXPIRED, now)
                        continue
                    entries.append({
                        "req": req,
                        # one slot row, host-landed: survives the old mesh
                        "page": np.asarray(
                            self._caches[:, :, slot:slot + 1]),
                        "tok": int(self._tok[slot]),
                        "p": int(self._p[slot]),
                        "limit": int(self._limit[slot]),
                        "left": int(self._left[slot]),
                    })
                heartbeat("elastic")
                # staged by the feed but never prefilled: keep the handles,
                # drop the staged arrays (adopt() re-stages them). The
                # producer drains _submit_q before ending, so polling to
                # StopIteration collects every admitted request.
                pending: List[ServingRequest] = []
                deadline = time.monotonic() + 10.0
                while self._feed is not None \
                        and time.monotonic() < deadline:
                    try:
                        item = self._feed.poll(timeout=0.2)
                    except StopIteration:
                        break
                    if item is not None:
                        pending.append(item[0])
                while True:            # belt and braces: producer died early
                    try:
                        pending.append(self._submit_q.get_nowait())
                    except queue.Empty:
                        break
                heartbeat("elastic")
            except BaseException:
                self._shutdown_sweep()
                raise
        if self._feed is not None:
            self._feed.close()
        if self._wd is not None:
            self._wd.stop()
        handoff = ServingHandoff(tot=self._TOT or 0, entries=entries,
                                 pending=pending)
        profiler.record_serving("drained", handoff.in_flight)
        tracer.instant("serving/drained", cat="serving",
                       args={"in_slots": len(entries),
                             "pending": len(pending)})
        return handoff

    def adopt(self, handoff: ServingHandoff) -> "ServingEngine":
        """Zero-drop handoff, half two: on a FRESH engine (same model,
        survivor mesh), reinstall each drained slot — KV page merged into a
        slot row, cursors restored — then start the scheduler and re-stage
        the pending requests. The adopted :class:`ServingRequest` handles
        are the originals, and ``_emit`` accounting is cumulative, so decode
        resumes exactly where the source engine stopped: greedy output stays
        bit-exact with an uninterrupted solo ``generate``."""
        with self._start_lock:
            if self._thread is not None:
                raise RuntimeError(
                    "adopt() needs a fresh engine (call before start/submit)")
            if len(handoff.entries) > self.slots:
                raise ValueError(
                    f"handoff carries {len(handoff.entries)} in-flight "
                    f"slots but this engine has {self.slots}")
            if handoff.entries:
                self._materialize_params()
                self._ensure_capacity(handoff.tot)
                for i, e in enumerate(handoff.entries):
                    self._caches = kv.merge_page(
                        self._caches, jnp.asarray(e["page"]), i)
                    self._tok[i] = e["tok"]
                    self._p[i] = e["p"]
                    self._limit[i] = e["limit"]
                    self._left[i] = e["left"]
                    self._active[i] = True
                    self._reqs[i] = e["req"]
        self.start()
        for req in handoff.pending:
            self._submit_q.put(req)     # blocking is fine: consumer is live
        profiler.record_serving("adopted", handoff.in_flight)
        tracer.instant("serving/adopted", cat="serving",
                       args={"in_slots": len(handoff.entries),
                             "pending": len(handoff.pending)})
        return self

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.stop()          # a latched scheduler error surfaces here
        else:
            try:
                self.stop()
            except BaseException:   # mxtpu: ignore[R005] — the body's
                pass                # exception wins over teardown's
        return False

    # -- staging (DeviceFeed producer thread) --------------------------------
    def _staging_source(self):
        """Blocking iterator the DeviceFeed producer pulls: pops submitted
        requests and pads their prompt to its 32-token bucket so the feed
        stages a device-resident ``(1, PB)`` int32 array per request."""
        while True:
            try:
                req = self._submit_q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            PB = kv.bucket32(len(req.prompt), self._model._max_len)
            padded = np.zeros((1, PB), np.int32)
            padded[0, :len(req.prompt)] = req.prompt
            yield (req, NDArray(padded))

    # -- scheduler thread ----------------------------------------------------
    def _materialize_params(self) -> None:
        pars = self._model.collect_params().values()
        if any(p._data is None for p in pars):
            from .. import autograd
            with autograd.predict_mode():
                self._model(NDArray(np.zeros((1, 1), np.int32)))
        self._params = self._model._gen_params()

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                heartbeat("serving")
                busy = bool(self._active.any())
                self._admit(wait_s=0.0 if busy else 0.02)
                if self._active.any():
                    self._decode_chunk()
        except BaseException as e:
            self._error = e
        finally:
            # a clean drain hands its in-flight state to adopt(); anything
            # else (stop, scheduler error) must cancel so nobody blocks
            if self._error is not None or not self._draining.is_set():
                self._shutdown_sweep()

    def _free_slot(self) -> Optional[int]:
        idle = np.flatnonzero(~self._active)
        return int(idle[0]) if idle.size else None

    def _admit(self, wait_s: float) -> None:
        while True:
            slot = self._free_slot()
            if slot is None or self._feed is None:
                return
            try:
                item = self._feed.poll(timeout=wait_s)
            except StopIteration:
                return
            if item is None:
                return
            wait_s = 0.0
            req, staged = item
            now = time.monotonic()
            if req._cancelled():
                req._finish(CANCELLED, now)
                profiler.record_serving("cancelled")
                continue
            if req._expired(now):
                req._finish(EXPIRED, now)
                profiler.record_serving("expired")
                continue
            self._prefill(req, staged, slot, now)

    def _prefill(self, req: ServingRequest, staged, slot: int,
                 now: float) -> None:
        model = self._model
        t0 = len(req.prompt)
        PB = staged.shape[1]
        req._set_state(RUNNING)
        profiler.record_serving("admitted")
        profiler.record_serving("queue_wait_ms_last",
                                (now - req.t_submit) * 1e3)
        self._ensure_capacity(kv.bucket32(req.total, model._max_len))
        with tracer.span("serving/prefill", cat="serving",
                         args={"id": req.id, "t0": t0, "bucket": PB}):
            fn = self._prefill_fns.get_or_build(
                (PB,), lambda: kv.build_prefill(model, PB))
            page, outs = fn(self._params, staged.data, jnp.int32(t0))
            outs_np = np.asarray(outs)
        done_t = time.monotonic()
        # prefill emits the tokens for positions t0..PB (see kv.py); a short
        # request can therefore complete at admission without taking a slot
        left = req._emit(outs_np[t0 - 1:].tolist(), done_t)
        delivered = req.max_new - left
        profiler.record_serving("prefills")
        profiler.record_serving("tokens_out", delivered)
        profiler.record_serving("ttft_ms_last",
                                (done_t - req.t_submit) * 1e3)
        if left == 0:
            req._finish(DONE, done_t)
            profiler.record_serving("completed")
            return
        self._caches = kv.merge_page(self._caches, page, slot)
        self._tok[slot] = outs_np[-1]        # the token at position PB
        self._p[slot] = PB                   # next position to feed
        self._limit[slot] = req.total - 1
        self._active[slot] = True
        self._left[slot] = left
        self._reqs[slot] = req

    def _ensure_capacity(self, need: int) -> None:
        if self._TOT is None:
            self._TOT = need
            self._caches = kv.empty_cache(self._model, self.slots, need)
        elif need > self._TOT:
            with tracer.span("serving/kv_promote", cat="serving",
                             args={"from": self._TOT, "to": need}):
                self._caches = kv.promote(self._caches, need)
            self._TOT = need
            profiler.record_serving("kv_promotions")

    def _decode_chunk(self) -> None:
        n_active = int(self._active.sum())
        with tracer.span("serving/decode", cat="serving",
                         args={"active": n_active, "tot": self._TOT}):
            key = (self.slots, self._TOT, self.chunk)
            fn = self._decode_fns.get_or_build(
                key, lambda: kv.build_decode(self._model, *key))
            caches, tok, p, toks, lives = fn(
                self._params, self._caches, jnp.asarray(self._tok),
                jnp.asarray(self._p), jnp.asarray(self._active),
                jnp.asarray(self._limit))
            toks_np = np.asarray(toks)
            lives_np = np.asarray(lives)
        self._caches = caches
        self._tok = np.array(tok)   # owned copies: the slot state is
        self._p = np.array(p)       # mutated at retire/admit boundaries
        now = time.monotonic()
        profiler.record_serving("decode_steps")
        profiler.record_serving_occupancy(n_active, self.slots)
        for slot in np.flatnonzero(self._active):
            req = self._reqs[slot]
            fresh = toks_np[lives_np[:, slot], slot]
            if fresh.size:
                left = req._emit(fresh.tolist(), now)
                profiler.record_serving("tokens_out",
                                        int(self._left[slot] - left))
                self._left[slot] = left
            if self._left[slot] == 0:
                self._retire(slot, DONE, now)
            elif req._cancelled():
                self._retire(slot, CANCELLED, now)
            elif req._expired(now):
                self._retire(slot, EXPIRED, now)

    def _retire(self, slot: int, state: str, now: float) -> None:
        req = self._reqs[slot]
        req._finish(state, now)
        profiler.record_serving({DONE: "completed", CANCELLED: "cancelled",
                                 EXPIRED: "expired"}[state])
        tracer.instant("serving/retire", cat="serving",
                       args={"id": req.id, "state": state})
        self._reqs[slot] = None
        self._active[slot] = False
        self._tok[slot] = 0
        self._p[slot] = 0
        self._limit[slot] = 0
        self._left[slot] = 0

    def _shutdown_sweep(self) -> None:
        """Terminal sweep: nothing submitted may block forever — in-slot,
        staged, and still-queued requests all finish CANCELLED."""
        self._stop.set()     # scheduler may exit via error with stop unset
        now = time.monotonic()
        for slot in np.flatnonzero(self._active):
            self._retire(int(slot), CANCELLED, now)
        # staged by the feed but never admitted: drain until the producer's
        # end marker (it sees the stop flag within its 0.1s poll)
        deadline = time.monotonic() + 5.0
        while self._feed is not None and time.monotonic() < deadline:
            try:
                item = self._feed.poll(timeout=0.2)
            except StopIteration:
                break
            except Exception:   # producer died mid-teardown: nothing to drain
                break
            if item is None:
                continue
            item[0]._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
        while True:                    # never even staged
            try:
                req = self._submit_q.get_nowait()
            except queue.Empty:
                break
            req._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
