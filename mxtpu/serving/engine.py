"""Continuous-batching serving engine (Orca-style step-boundary scheduling,
Sarathi-style decode-overlapped chunked prefill, SGLang-style radix prefix
reuse).

:class:`ServingEngine` is the online front door over a decode-capable model
(anything exposing ``serving_step`` / ``serving_sample`` / ``_gen_params`` —
``TransformerLM`` in the zoo): callers ``submit()`` token prompts from any
thread; one scheduler thread runs the slot batch.

The data path, end to end:

1. **Admission** — ``submit()`` drops the request into a bounded queue
   (full → :exc:`QueueFullError`, the backpressure contract). A
   :class:`DeviceFeed` producer stages each prompt device-resident (padded
   to its 32-token bucket) so admission never pays a host→device transfer
   inside the decode loop; the scheduler drains it with the non-blocking
   ``poll()``.
2. **Chunked prefill** — the prompt runs through a separate B=1 program in
   fixed-budget position chunks (``kv.build_prefill_chunk``, one program per
   (prompt bucket, chunk size)), ONE chunk dispatched between decode chunks:
   a partial-prefill cursor lives on the reserved slot, so a long prompt
   never stalls the in-flight slot batch for more than one chunk's work (the
   decode-stall guard bound). Before the first chunk the radix
   :class:`~mxtpu.serving.kv.PrefixCache` is probed: a prompt extending a
   cached prefix copies the cached K/V rows into its page and prefills only
   the suffix — a shared system prompt costs one prefill, ever. The finished
   page is merged into the slot row; forced-prompt blocks are inserted back
   into the tree.
3. **Decode** — ``kv.build_decode`` runs ``chunk`` steps over ALL slots per
   dispatch; per-slot token/position/active/limit AND sampling params
   (temperature/top-k/seed) are traced inputs, so requests retiring,
   joining, or changing the sampling mix between dispatches reuse the same
   compiled program (ONE trace per (slots, TOT bucket) — the compile-guard
   contract). Greedy slots stay bit-exact with solo ``generate``; sampled
   slots are deterministic per (seed, position). Finished/cancelled/expired
   requests retire at chunk boundaries and their slots are immediately
   re-admissible.

Guardrails: every dispatch heartbeats the resilience watchdog on the
``serving`` source (arm with ``MXTPU_SERVING_STALL_S``), spans land in the
unified trace under ``serving/*`` (``prefill_chunk``, ``decode``,
``prefix_hit``…), and counters — including the TTFT decomposition
queue-wait / prefill / first-decode-token — in
``profiler.get_serving_stats()``.

Live elasticity (ROADMAP item 4, ``docs/resilience.md``): ``drain()`` stops
admission, parks the scheduler at a chunk boundary, and freezes every
in-flight request — its KV page, next-token/position/limit/sampling slot
state, and handle, including a PARTIALLY-PREFILLED request's cursor and
partial page — into a :class:`ServingHandoff`; ``adopt()`` on a fresh engine
(same model, survivor mesh) reinstalls the pages and resumes decoding (or
the suffix prefill) for the SAME request handles bit-exactly, with zero
drops. Queued-but-unprefilled requests ride along and are re-staged on the
adopting engine.

Knobs: ``MXTPU_SERVING_SLOTS`` (slot-batch capacity, default 4),
``MXTPU_SERVING_QUEUE`` (admission queue depth, default 16),
``MXTPU_SERVING_CHUNK`` (decode steps per dispatch, default 8),
``MXTPU_SERVING_PREFILL_CHUNK`` (prefill positions per dispatch, default
64), ``MXTPU_PREFIX_CACHE_MB`` (radix prefix-cache byte cap, default 64; 0
disables), ``MXTPU_SERVING_LOG_S`` (per-interval engine log period, default
off), ``MXTPU_SERVING_PROGRAM_CACHE`` (LRU bound on the program caches),
``MXTPU_SERVING_KV_DTYPE`` (cache storage dtype, e.g. ``bfloat16``),
``MXTPU_SERVING_QUANT`` (low-precision execution: ``int8_kv`` / ``fp8_kv``
/ ``int8_w``, comma-separated — see ``docs/quantization.md``). All knobs
are also settable programmatically via :class:`~mxtpu.serving.api
.ServingConfig` / the constructor kwargs.
"""

from __future__ import annotations

import itertools
import logging
import os
import queue
import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from .. import profiler
from ..device_feed import DeviceFeed
from ..ndarray.ndarray import NDArray
from ..observability import tracer
from ..resilience.elastic import elastic_watchdog
from ..resilience.faults import fault_point
from ..ops import quant_attention
from ..quant.serve import parse_quant, quantize_lm
from ..resilience.watchdog import Watchdog, heartbeat
from ..step_cache import ProgramCache
from . import kv
from .api import (CANCELLED, DONE, EXPIRED, PENDING, RUNNING, SHED,
                  HandoffMismatch, QueueFullError, ServingConfig,
                  ServingRequest)
from .spec import NgramDrafter, parse_spec, spec_from_env

__all__ = ["ServingEngine", "ServingHandoff"]

_log = logging.getLogger("mxtpu.serving")

# replica ids minted at construction (satellite of the router work): every
# serving metric series carries this label so N scraped replicas never
# collide on one series name; a fronting Router overrides it per replica
_ENGINE_IDS = itertools.count()


@dataclass
class ServingHandoff:
    """Frozen in-flight serving state from :meth:`ServingEngine.drain`,
    consumable by :meth:`ServingEngine.adopt` on a fresh engine. Everything
    is host-resident (pages are numpy), so the handoff survives the source
    mesh disappearing entirely."""
    tot: int                                  # KV bucket length of each page
    entries: List[dict] = field(default_factory=list)   # per in-flight slot:
    #   req / page (L,2,1,H,tot,D np) / tok / p / limit / left / temp/topk/seed
    partial: List[dict] = field(default_factory=list)   # mid-prefill request:
    #   req / page (L,2,1,H,PB,D np) / t (cursor) / prev / t0 / PB / left —
    #   adopt() resumes the SUFFIX prefill, never re-prefills from scratch
    pending: List[ServingRequest] = field(default_factory=list)  # admitted,
    #   never prefilled — re-staged verbatim by adopt(). The request handles
    #   everywhere in this handoff carry their own scheduling metadata
    #   (tenant / priority / deadline), so SLO state survives the hop
    kv_dtype: str = "float32"                 # page storage: 'float32' /
    #   'bfloat16' / 'int8' / 'fp8' — adopt() refuses a mismatched engine
    #   (quantized pages are QuantKV hosts; reinterpreting them as another
    #   storage would corrupt every resumed request)
    parked: List[dict] = field(default_factory=list)  # preempted decode
    #   slots (mxtpu.sched): same shape as `entries` plus the park-time
    #   "tot" — adopt() re-queues them for resume, sched-enabled engines only
    sched_state: Optional[dict] = None        # SLOScheduler.export_state():
    #   fair-share passes + service-rate EWMAs, so the successor's policy
    #   doesn't restart cold
    spec: Optional[dict] = None               # speculative-decode state of the
    #   source engine ({"k": draft depth}); entries/parked then also carry
    #   per-slot "draft" (proposed tokens) + "dlen" (how many are live). The
    #   verify cursor is the entry's own "p" — drafts are proposed BETWEEN
    #   dispatches, so a drained slot's p is always at a verify boundary and
    #   its in-flight drafts are pure proposals (no K/V written for them
    #   yet). adopt() on a spec-less engine refuses in-flight drafts, the
    #   parked-slots rule's mirror; a spec engine with a different k safely
    #   truncates or re-proposes (drafts are advisory by construction)
    mesh: Optional[tuple] = None              # sharded.mesh_fingerprint() of
    #   the source engine (None = single-device): adopt() refuses a
    #   mismatched successor with HandoffMismatch UP FRONT — single-device
    #   and sharded engines never silently exchange placement assumptions
    kv_geometry: Optional[tuple] = None       # (L, H, D) cache-row geometry
    #   of the source model; page shapes are validated against the adopting
    #   model BEFORE any merge, so a wrong-geometry handoff is a named
    #   error, never a shape crash mid-adopt

    @property
    def in_flight(self) -> int:
        return (len(self.entries) + len(self.partial) + len(self.pending)
                + len(self.parked))


def _env_int(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, str(default))))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _req_sampling(req: ServingRequest):
    sp = req.sampling
    if sp is None:
        return 0.0, 0, 0
    return float(sp.temperature), int(sp.top_k), int(sp.seed)


class ServingEngine:
    """Online continuous-batching server over one decode-capable model.

    Greedy decoding is the bit-exact default (argmax vs solo ``generate``);
    per-request :class:`~mxtpu.serving.api.SamplingParams` ride the decode
    program as per-slot traced arrays, seed-deterministic regardless of
    slot assignment or chunk boundaries."""

    def __init__(self, model, slots: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 chunk: Optional[int] = None,
                 stall_deadline_s: Optional[float] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache_mb: Optional[float] = None,
                 kv_dtype=None, quant=None, decode_kernel=None,
                 sched=None, prefill_batch: Optional[int] = None,
                 spec=None, mesh=None, engine_id: Optional[str] = None,
                 config: Optional[ServingConfig] = None):
        if config is not None:
            slots = slots or config.slots
            queue_depth = queue_depth or config.queue_depth
            chunk = chunk or config.chunk
            prefill_chunk = prefill_chunk or config.prefill_chunk
            if prefix_cache_mb is None:
                prefix_cache_mb = config.prefix_cache_mb
            if stall_deadline_s is None:
                stall_deadline_s = config.stall_deadline_s
            kv_dtype = kv_dtype or config.kv_dtype
            if quant is None:
                quant = config.quant
            if decode_kernel is None:
                decode_kernel = config.decode_kernel
            if sched is None:
                sched = config.sched
            if prefill_batch is None:
                prefill_batch = config.prefill_batch
            if spec is None:
                spec = config.spec
            if mesh is None:
                mesh = config.mesh
            engine_id = engine_id or config.engine_id
        self._model = model
        # per-replica metric label (observability): minted here so every
        # serving series this engine records carries a stable id from the
        # first dispatch; a fronting Router names its replicas through this
        self.engine_id = engine_id or f"engine{next(_ENGINE_IDS)}"
        # speculative multi-token decode (mxtpu.serving.spec): like quant,
        # ONE resolved config per engine lifetime (kwarg > config >
        # MXTPU_SPEC_DECODE env) — the verify program cache stays keyed on
        # (slots, bucket, k); None keeps every path below byte-identical
        self._spec = parse_spec(spec) if spec is not None else spec_from_env()
        self._drafter = (self._spec.drafter
                         if self._spec is not None else None)
        # low-precision execution (mxtpu.quant): ONE spec per engine
        # lifetime, resolved kwarg > config > env — the program caches stay
        # keyed on (slots, bucket, chunk) because the spec never changes
        if quant is None:
            quant = os.environ.get("MXTPU_SERVING_QUANT") or None
        self._quant = parse_quant(quant)
        # fused dequant-attention path of the quantized KV read: like the
        # spec, resolved ONCE per engine lifetime (kwarg > config >
        # MXTPU_DECODE_KERNEL env) — an env flip while serving can never
        # reach a live program, let alone retrace it
        self._decode_kernel = quant_attention.decode_kernel_mode(decode_kernel)
        # model-parallel serving (mxtpu.serving.sharded): ONE mesh per
        # engine lifetime — params, the paged KV, and every compiled
        # program place onto it at materialization, and each dispatch
        # traces under fsdp.layout_scope so the step functions' activation
        # constraints fire. mesh=None keeps every path below byte-identical
        self._mesh = mesh
        self._layout = None
        if mesh is not None:
            from . import sharded
            sharded.validate_mesh(mesh)
            self._layout = sharded.ServingLayout()
            if self._quant.kv:
                # the fused pallas read is refused under a mesh; auto pins
                # the GSPMD-partitionable xla read (named error, up front)
                self._decode_kernel = sharded.pin_decode_kernel(
                    self._decode_kernel)
        self._decode_kernel_str = (
            quant_attention.resolve_decode_kernel(self._decode_kernel)
            if self._quant.kv else None)
        if kv_dtype is None:
            kv_dtype = os.environ.get("MXTPU_SERVING_KV_DTYPE") or None
        self._kv_dtype = jnp.zeros((0,), kv_dtype or jnp.float32).dtype
        # what get_serving_stats()/ServingHandoff report as the page storage
        self._kv_dtype_str = self._quant.kv or self._kv_dtype.name
        self.slots = slots if slots else _env_int("MXTPU_SERVING_SLOTS", 4)
        self.queue_depth = queue_depth if queue_depth \
            else _env_int("MXTPU_SERVING_QUEUE", 16)
        self.chunk = chunk if chunk else _env_int("MXTPU_SERVING_CHUNK", 8)
        self.prefill_chunk = prefill_chunk if prefill_chunk \
            else _env_int("MXTPU_SERVING_PREFILL_CHUNK", 64)
        self.prefix_cache_mb = prefix_cache_mb if prefix_cache_mb is not None \
            else _env_float("MXTPU_PREFIX_CACHE_MB", 64.0)
        if stall_deadline_s is None:
            raw = os.environ.get("MXTPU_SERVING_STALL_S", "")
            stall_deadline_s = float(raw) if raw else None
        self._stall_deadline_s = stall_deadline_s
        self._log_s = _env_float("MXTPU_SERVING_LOG_S", 0.0)
        self._next_log = 0.0
        self._submit_q: "queue.Queue" = queue.Queue(maxsize=self.queue_depth)
        self._start_lock = threading.Lock()
        self._decode_fns = ProgramCache("serving_decode")
        self._prefill_fns = ProgramCache("serving_prefill")
        self._verify_fns = ProgramCache("serving_verify")
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._feed: Optional[DeviceFeed] = None
        self._wd: Optional[Watchdog] = None
        self._error: Optional[BaseException] = None
        # slot state (scheduler-thread-owned; riders of the decode trace)
        self._params = None
        self._caches = None
        self._TOT: Optional[int] = None
        self._tok = np.zeros(self.slots, np.int32)
        self._p = np.zeros(self.slots, np.int32)
        self._limit = np.zeros(self.slots, np.int32)
        self._active = np.zeros(self.slots, bool)
        self._left = np.zeros(self.slots, np.int64)
        self._temp = np.zeros(self.slots, np.float32)
        self._topk = np.zeros(self.slots, np.int32)
        self._seed = np.zeros(self.slots, np.uint32)
        self._t_admit = np.zeros(self.slots, np.float64)
        self._dec_emitted = np.zeros(self.slots, bool)
        self._reqs: List[Optional[ServingRequest]] = [None] * self.slots
        # per-slot speculative draft buffers (scheduler-thread-owned):
        # proposed at the END of a decode turn, consumed by the next verify
        # dispatch — so a drain() between turns carries genuine in-flight
        # drafts. dlen == 0 means "plain decode this turn" for the slot
        if self._spec is not None:
            self._draft = np.zeros((self.slots, self._spec.k), np.int32)
            self._dlen = np.zeros(self.slots, np.int32)
        self._ngram_hits_seen = 0
        self._ngram_misses_seen = 0
        # partial-prefill cursor (scheduler-thread-owned; at most one
        # request prefills at a time, one CHUNK dispatched per loop turn)
        self._pf: Optional[dict] = None
        self._prefix: Optional[kv.PrefixCache] = None
        self._evict_seen = 0
        # SLO control plane (mxtpu.sched) — strictly opt-in: with sched
        # unset every code path below is byte-identical to the plain FIFO
        # engine (the sched package is imported only when enabled)
        self._sched = None
        if sched:
            from ..sched.policy import SLOPolicy, SLOScheduler
            if sched is True:
                self._sched = SLOScheduler()
            elif isinstance(sched, SLOScheduler):
                self._sched = sched
            elif isinstance(sched, SLOPolicy):
                self._sched = SLOScheduler(sched)
            else:
                raise ValueError(
                    "sched must be True, an SLOPolicy, or an SLOScheduler; "
                    f"got {type(sched).__name__}")
        self._prefill_batch = int(prefill_batch) if prefill_batch else 1
        if self._prefill_batch > 1 and self._sched is None:
            raise ValueError("prefill_batch > 1 requires the SLO scheduler "
                             "(pass sched=True / a policy)")
        # staged (req, prompt) pairs awaiting a fair-share pick; preempted
        # decode slots parked for resume; in-flight batched prefill group
        # (all scheduler-thread-owned, sched mode only)
        self._sched_pending: List[tuple] = []
        self._parked: List[dict] = []
        self._pfg = None

    # -- public surface ------------------------------------------------------
    def start(self) -> "ServingEngine":
        with self._start_lock:
            if self._thread is not None:
                return self
            self._materialize_params()
            profiler.record_serving("slots", self.slots)
            profiler.record_serving("engine", self.engine_id)
            profiler.record_serving("kv_dtype", self._kv_dtype_str)
            if self._decode_kernel_str is not None:
                profiler.record_serving("decode_kernel",
                                        self._decode_kernel_str)
            self._feed = DeviceFeed(self._staging_source(), depth=2)
            if self._stall_deadline_s:
                self._wd = Watchdog(deadline_s=self._stall_deadline_s,
                                    source="serving").start()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="mxtpu-serving-scheduler")
            self._thread.start()
            self._started.set()
        return self

    def submit(self, prompt, max_new_tokens: int,
               deadline_s: Optional[float] = None,
               sampling=None, prefix_cache: bool = True,
               tenant: str = "default",
               priority: str = "standard") -> ServingRequest:
        """Enqueue one generation request; returns its handle immediately.
        ``sampling`` takes :class:`~mxtpu.serving.api.SamplingParams` (or a
        mapping of its fields; omitted = bit-exact greedy);
        ``prefix_cache=False`` opts the request out of shared-prefix KV
        reuse in both directions. ``tenant``/``priority`` are the SLO
        scheduling keys (inert without ``sched=...``; see
        :class:`~mxtpu.serving.api.ServingRequest`). Raises
        :exc:`QueueFullError` when the admission queue is at capacity
        (backpressure, not silent growth) and ``ValueError`` for requests
        the model can't hold."""
        if self._draining.is_set():
            raise RuntimeError(
                "ServingEngine is draining — submit to the adopting engine")
        if self._stop.is_set():
            raise RuntimeError("ServingEngine is stopped")
        req = ServingRequest(prompt, max_new_tokens, deadline_s,
                             sampling=sampling, prefix_cache=prefix_cache,
                             tenant=tenant, priority=priority)
        if req.total > self._model._max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + {req.max_new} new exceeds "
                f"max_len {self._model._max_len}")
        if self._thread is None:
            self.start()
        try:
            self._submit_q.put_nowait(req)
        except queue.Full:
            profiler.record_serving("rejected")
            tracer.instant("serving/reject", cat="serving",
                           args={"id": req.id})
            raise QueueFullError(
                f"admission queue full ({self.queue_depth}); request "
                f"{req.id} rejected") from None
        profiler.record_serving("submitted")
        profiler.record_serving("queue_depth_max", self._submit_q.qsize())
        tracer.instant("serving/submit", cat="serving",
                       args={"id": req.id, "prompt": len(req.prompt),
                             "max_new": req.max_new})
        return req

    def stats(self) -> dict:
        return profiler.get_serving_stats()

    def load(self) -> dict:
        """Cheap load signal for a fronting :class:`~mxtpu.serving.router
        .Router`: queued admissions plus occupied/reserved work, plus the
        queue bound so the router can reason about headroom. Lock-free
        snapshot reads — safe from any thread, never blocks the scheduler
        (the R010 contract: routers poll, they don't block a decode
        turn)."""
        active = int(self._active.sum())
        waiting = (self._submit_q.qsize()
                   + (1 if self._pf is not None else 0)
                   + (len(self._pfg.members) if self._pfg is not None else 0)
                   + len(self._sched_pending) + len(self._parked))
        return {"engine": self.engine_id, "active": active,
                "queued": waiting, "slots": self.slots,
                "queue_depth": self.queue_depth,
                "in_flight": active + waiting}

    def request_timeline(self, rid: int) -> List[dict]:
        """Every trace event tagged with request ``rid``, time-sorted —
        submit → admit → prefill chunks → decode dispatches → retire,
        including drain/adopt markers when the request crossed an engine
        handoff. Needs tracing on (``profiler.start()`` / ``MXTPU_TRACE``);
        ids also land in the batch ``serving/decode`` spans, so a request's
        lane shows exactly which dispatches computed its tokens."""
        from ..observability import export
        return export.request_timeline(rid)

    def stop(self) -> None:
        """Stop the scheduler; queued and in-flight requests are finished
        as CANCELLED so no caller blocks forever."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
        if self._feed is not None:
            self._feed.close()
        if self._wd is not None:
            self._wd.stop()
        if self._error is not None:
            raise self._error

    def drain(self) -> ServingHandoff:
        """Zero-drop handoff, half one: stop admission (``submit`` raises),
        park the scheduler at its chunk boundary, and freeze every live
        request — KV page, slot cursors, sampling params, handle, and a
        mid-prefill request's partial page + cursor — into a host-resident
        :class:`ServingHandoff` for :meth:`adopt` on a successor engine.
        No request is cancelled; callers blocked in ``result()`` simply keep
        waiting across the handoff. Runs under the ``elastic`` heartbeat
        source (``MXTPU_ELASTIC_STALL_S``) and the ``serving.drain`` fault
        seam; on any failure the normal cancel-everything sweep runs before
        the error propagates, so the no-caller-blocks-forever contract holds
        even when the handoff itself dies."""
        if self._thread is None:
            raise RuntimeError("ServingEngine is not started")
        with tracer.span("serving/drain", cat="serving"), elastic_watchdog():
            heartbeat("elastic")
            self._draining.set()      # submit() now raises
            self._stop.set()          # scheduler exits at the chunk boundary
            self._thread.join(timeout=60)
            if self._error is not None:
                raise self._error     # sweep already ran in the scheduler
            try:
                fault_point("serving.drain")
                # an in-flight batched prefill group is finished HERE, one
                # chunk per turn (bounded: the cursor only advances), so its
                # survivors freeze below as ordinary in-slot entries
                while self._pfg is not None:
                    self._prefill_group_chunk()
                now = time.monotonic()
                entries: List[dict] = []
                for slot in np.flatnonzero(self._active):
                    slot = int(slot)
                    req = self._reqs[slot]
                    if req._cancelled():
                        self._retire(slot, CANCELLED, now)
                        continue
                    if req._expired(now):
                        self._retire(slot, EXPIRED, now)
                        continue
                    entry = {
                        "req": req,
                        # one slot row, host-landed: survives the old mesh
                        # (quantized pages keep their data + scale leaves)
                        "page": kv.host_page(
                            kv.slot_page(self._caches, slot)),
                        "tok": int(self._tok[slot]),
                        "p": int(self._p[slot]),
                        "limit": int(self._limit[slot]),
                        "left": int(self._left[slot]),
                        "temp": float(self._temp[slot]),
                        "topk": int(self._topk[slot]),
                        "seed": int(self._seed[slot]),
                    }
                    if self._spec is not None:
                        # the slot's in-flight drafts (proposed at the end
                        # of the last turn, not yet verified) ride along;
                        # "p" doubles as the verify cursor — see the
                        # ServingHandoff.spec field note
                        entry["draft"] = self._draft[slot].tolist()
                        entry["dlen"] = int(self._dlen[slot])
                    entries.append(entry)
                    tracer.instant("serving/drain_freeze", cat="serving",
                                   args={"id": req.id, "slot": slot,
                                         "p": int(self._p[slot])})
                # a partially-prefilled admission carries its cursor +
                # already-computed page rows — adopt() resumes the SUFFIX
                partial: List[dict] = []
                if self._pf is not None:
                    pf, self._pf = self._pf, None
                    req = pf["req"]
                    if req._cancelled():
                        req._finish(CANCELLED, now)
                        profiler.record_serving("cancelled")
                    elif req._expired(now):
                        req._finish(EXPIRED, now)
                        profiler.record_serving("expired")
                    else:
                        partial.append({
                            "req": req,
                            "page": kv.host_page(pf["page"]),
                            "t": pf["t"], "prev": pf["prev"],
                            "t0": pf["t0"], "PB": pf["PB"],
                            "left": pf["left"],
                        })
                        tracer.instant("serving/drain_freeze", cat="serving",
                                       args={"id": req.id, "partial": True,
                                             "t": pf["t"]})
                heartbeat("elastic")
                # staged by the feed but never prefilled: keep the handles,
                # drop the staged arrays (adopt() re-stages them). The
                # producer drains _submit_q before ending, so polling to
                # StopIteration collects every admitted request.
                pending: List[ServingRequest] = []
                deadline = time.monotonic() + 10.0
                while self._feed is not None \
                        and time.monotonic() < deadline:
                    try:
                        item = self._feed.poll(timeout=0.2)
                    except StopIteration:
                        break
                    if item is not None:
                        pending.append(item[0])
                while True:            # belt and braces: producer died early
                    try:
                        pending.append(self._submit_q.get_nowait())
                    except queue.Empty:
                        break
                # sched mode: staged-but-unpicked requests ride as pending;
                # preempted (parked) slots host-land like entries
                pending.extend(r for r, _s in self._sched_pending)
                self._sched_pending = []
                parked = [{**e, "page": kv.host_page(e["page"])}
                          for e in self._parked]
                self._parked = []
                heartbeat("elastic")
            except BaseException:
                self._shutdown_sweep()
                raise
        if self._feed is not None:
            self._feed.close()
        if self._wd is not None:
            self._wd.stop()
        from . import sharded
        handoff = ServingHandoff(
            tot=self._TOT or 0, entries=entries, partial=partial,
            pending=pending, kv_dtype=self._kv_dtype_str, parked=parked,
            sched_state=self._sched.export_state()
            if self._sched is not None else None,
            spec={"k": self._spec.k} if self._spec is not None else None,
            mesh=sharded.mesh_fingerprint(self._mesh),
            kv_geometry=kv.cache_dims(self._model))
        profiler.record_serving("drained", handoff.in_flight)
        tracer.instant("serving/drained", cat="serving",
                       args={"in_slots": len(entries),
                             "partial": len(partial),
                             "pending": len(pending),
                             "parked": len(parked),
                             "ids": [e["req"].id for e in entries]
                             + [e["req"].id for e in partial]
                             + [r.id for r in pending]
                             + [e["req"].id for e in parked]})
        return handoff

    def adopt(self, handoff: ServingHandoff) -> "ServingEngine":
        """Zero-drop handoff, half two: on a FRESH engine (same model,
        survivor mesh), reinstall each drained slot — KV page merged into a
        slot row, cursors and sampling params restored — resume a
        mid-prefill request from its cursor (suffix only, never from
        scratch), then start the scheduler and re-stage the pending
        requests. The adopted :class:`ServingRequest` handles are the
        originals, and ``_emit`` accounting is cumulative, so decode
        resumes exactly where the source engine stopped: greedy output
        stays bit-exact with an uninterrupted solo ``generate``."""
        with self._start_lock:
            if self._thread is not None:
                raise RuntimeError(
                    "adopt() needs a fresh engine (call before start/submit)")
            if len(handoff.entries) + len(handoff.partial) > self.slots:
                raise ValueError(
                    f"handoff carries {len(handoff.entries)} in-flight + "
                    f"{len(handoff.partial)} mid-prefill slots but this "
                    f"engine has {self.slots}")
            if handoff.kv_dtype != self._kv_dtype_str:
                raise ValueError(
                    f"handoff pages are {handoff.kv_dtype} but this engine "
                    f"stores KV as {self._kv_dtype_str} — adopt on an "
                    "engine with the same kv_dtype/quant configuration")
            self._validate_handoff(handoff)
            if handoff.parked and self._sched is None:
                raise ValueError(
                    "handoff carries preempted (parked) requests — adopt on "
                    "an engine with the SLO scheduler enabled (sched=...)")
            # mirror of the parked rule for speculation: in-flight drafts are
            # proposals only (no K/V behind them — "p" is the verify cursor),
            # but a spec-less engine has no verify program to consume them
            # and silently dropping speculative state is how handoffs rot
            in_flight_drafts = sum(
                int(e.get("dlen") or 0)
                for e in list(handoff.entries) + list(handoff.parked))
            if in_flight_drafts and self._spec is None:
                raise ValueError(
                    "handoff carries in-flight speculative drafts — adopt on "
                    "an engine with speculative decode enabled (spec=...)")
            if self._sched is not None:
                if handoff.sched_state:
                    self._sched.load_state(handoff.sched_state)
                # re-register every surviving handle so fair-share charging
                # and R008-shaped inflight tracking pick up where drain left
                for req in ([e["req"] for e in handoff.entries]
                            + [e["req"] for e in handoff.partial]
                            + [e["req"] for e in handoff.parked]):
                    self._sched.register(req)
                self._parked.extend(dict(e) for e in handoff.parked)
            if handoff.entries or handoff.partial:
                self._materialize_params()
            if handoff.entries:
                self._ensure_capacity(handoff.tot)
                for i, e in enumerate(handoff.entries):
                    self._merge_page(kv.device_page(e["page"]), i)
                    self._tok[i] = e["tok"]
                    self._p[i] = e["p"]
                    self._limit[i] = e["limit"]
                    self._left[i] = e["left"]
                    self._temp[i] = e.get("temp", 0.0)
                    self._topk[i] = e.get("topk", 0)
                    self._seed[i] = e.get("seed", 0)
                    self._t_admit[i] = time.monotonic()
                    self._dec_emitted[i] = False
                    if self._spec is not None and e.get("dlen"):
                        # a k mismatch truncates (advisory proposals — the
                        # verify program re-scores whatever survives)
                        n = min(int(e["dlen"]), self._spec.k)
                        self._draft[i, :n] = e["draft"][:n]
                        self._dlen[i] = n
                    self._active[i] = True
                    self._reqs[i] = e["req"]
                    tracer.instant("serving/adopt_resume", cat="serving",
                                   args={"id": e["req"].id, "slot": i,
                                         "p": e["p"]})
            if handoff.partial:
                e = handoff.partial[0]
                req = e["req"]
                padded = np.zeros((1, e["PB"]), np.int32)
                padded[0, :len(req.prompt)] = req.prompt
                temp, topk, seed = _req_sampling(req)
                self._pf = {"req": req, "prompt": jnp.asarray(padded),
                            "page": kv.device_page(e["page"]),
                            "t": e["t"], "prev": e["prev"],
                            "t0": e["t0"], "PB": e["PB"], "left": e["left"],
                            "slot": len(handoff.entries),
                            "t_start": time.monotonic(),
                            "temp": temp, "topk": topk, "seed": seed}
                tracer.instant("serving/adopt_resume", cat="serving",
                               args={"id": req.id, "partial": True,
                                     "t": e["t"]})
        self.start()
        for req in handoff.pending:
            self._submit_q.put(req)     # blocking is fine: consumer is live
        profiler.record_serving("adopted", handoff.in_flight)
        tracer.instant("serving/adopted", cat="serving",
                       args={"in_slots": len(handoff.entries),
                             "partial": len(handoff.partial),
                             "pending": len(handoff.pending),
                             "ids": [e["req"].id for e in handoff.entries]
                             + [e["req"].id for e in handoff.partial]
                             + [r.id for r in handoff.pending]})
        return self

    def _validate_handoff(self, handoff: ServingHandoff) -> None:
        """Up-front handoff compatibility: mesh/sharding fingerprint and KV
        page geometry are checked BEFORE any page merges, so an incompatible
        adopt is a :class:`~mxtpu.serving.api.HandoffMismatch` naming the
        mismatch — never a shape crash halfway through reinstalling slots
        (which would strand the already-merged requests)."""
        from . import sharded
        mine = sharded.mesh_fingerprint(self._mesh)
        if handoff.mesh != mine:
            def _name(fp):
                return ("single-device" if fp is None
                        else "x".join(f"{a}={n}" for a, n in fp))
            raise HandoffMismatch(
                f"handoff was drained from a {_name(handoff.mesh)} engine "
                f"but this engine is {_name(mine)} — drained pages only "
                "re-place onto the same mesh geometry; adopt on a matching "
                "engine (or drain/adopt through a host round-trip tool)")
        geo = kv.cache_dims(self._model)
        if handoff.kv_geometry is not None and \
                tuple(handoff.kv_geometry) != tuple(geo):
            raise HandoffMismatch(
                f"handoff KV rows have (layers, heads, head_dim) = "
                f"{tuple(handoff.kv_geometry)} but this engine's model "
                f"has {tuple(geo)} — same-model adoption only")
        L, H, D = geo

        def _shape(page):
            return tuple(getattr(page, "data", page).shape)

        for kind, tot_of, lst in (
                ("in-flight", lambda e: handoff.tot, handoff.entries),
                ("mid-prefill", lambda e: e["PB"], handoff.partial),
                ("parked", lambda e: e["tot"], handoff.parked)):
            for e in lst:
                page = e.get("page")
                if page is None:     # page-less entry (e.g. a spec-only
                    continue         # probe handoff) — nothing to re-place
                want = (L, 2, 1, H, tot_of(e), D)
                got = _shape(page)
                if got != want:
                    raise HandoffMismatch(
                        f"{kind} page for request {e['req'].id} has shape "
                        f"{got}, expected {want} — the handoff does not "
                        "match this engine's model/bucket geometry")

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.stop()          # a latched scheduler error surfaces here
        else:
            try:
                self.stop()
            except BaseException:   # mxtpu: ignore[R005] — the body's
                pass                # exception wins over teardown's
        return False

    # -- staging (DeviceFeed producer thread) --------------------------------
    def _staging_source(self):
        """Blocking iterator the DeviceFeed producer pulls: pops submitted
        requests and pads their prompt to its 32-token bucket so the feed
        stages a device-resident ``(1, PB)`` int32 array per request."""
        while True:
            try:
                req = self._submit_q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            PB = kv.bucket32(len(req.prompt), self._model._max_len)
            padded = np.zeros((1, PB), np.int32)
            padded[0, :len(req.prompt)] = req.prompt
            yield (req, NDArray(padded))

    # -- scheduler thread ----------------------------------------------------
    def _materialize_params(self) -> None:
        pars = self._model.collect_params().values()
        if any(p._data is None for p in pars):
            from .. import autograd
            with autograd.predict_mode():
                self._model(NDArray(np.zeros((1, 1), np.int32)))
        # identity pass-through on the fp32 path; int8 per-channel weights +
        # scales under int8_w (one host-side pass, then everything is traced)
        self._params = quantize_lm(self._model, self._quant)
        if self._mesh is not None:
            # one-time placement onto the SpecLayout table (column-parallel
            # sharded, row-parallel replicated — mxtpu/serving/sharded.py);
            # params ride every program as ALREADY-PLACED jit arguments, so
            # the first trace keys on the canonical shardings
            from . import sharded
            self._params = sharded.place_params(self._params, self._mesh,
                                                self._layout)
        if self._prefix is None and self.prefix_cache_mb > 0:
            block_bytes = kv.block_nbytes(self._model, self._kv_dtype,
                                          self._quant)
            self._prefix = kv.PrefixCache(block_bytes, self.prefix_cache_mb)
        if self._spec is not None and self._drafter is None:
            # default drafter: radix-tree n-grams + self-context lookup;
            # works with the prefix cache disabled too (self-context only)
            self._drafter = NgramDrafter.from_config(self._spec, self._prefix)

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                heartbeat("serving")
                busy = bool(self._active.any()) or self._pf is not None \
                    or self._pfg is not None
                self._admit(wait_s=0.0 if busy else 0.02)
                if self._pf is not None:
                    self._prefill_chunk()     # ONE chunk, then yield to
                elif self._pfg is not None:   # decode: the stall bound
                    self._prefill_group_chunk()
                if self._active.any():
                    if self._spec is not None:
                        self._spec_decode_turn()
                    else:
                        self._decode_chunk()
                self._maybe_log()
        except BaseException as e:
            self._error = e
            from ..observability import flight
            flight.record("scheduler_error", error=repr(e))
            flight.dump("scheduler_error", extra={"error": repr(e)})
        finally:
            # a clean drain hands its in-flight state to adopt(); anything
            # else (stop, scheduler error) must cancel so nobody blocks
            if self._error is not None or not self._draining.is_set():
                self._shutdown_sweep()

    def _free_slot(self, exclude=()) -> Optional[int]:
        reserved = set(exclude)
        if self._pf is not None:
            reserved.add(self._pf["slot"])
        if self._pfg is not None:
            reserved.update(m["slot"] for m in self._pfg.members)
        for i in range(self.slots):
            if not self._active[i] and i not in reserved:
                return i
        return None

    def _admit(self, wait_s: float) -> None:
        """Start at most one partial prefill per loop turn: pop a staged
        request, probe the prefix cache, reserve a slot, and leave the
        cursor for :meth:`_prefill_chunk` to advance between decodes."""
        if self._sched is not None:
            self._admit_sched(wait_s)
            return
        while self._pf is None:
            slot = self._free_slot()
            if slot is None or self._feed is None:
                return
            try:
                item = self._feed.poll(timeout=wait_s)
            except StopIteration:
                return
            if item is None:
                return
            wait_s = 0.0
            req, staged = item
            now = time.monotonic()
            if req._cancelled():
                req._finish(CANCELLED, now)
                profiler.record_serving("cancelled")
                continue
            if req._expired(now):
                req._finish(EXPIRED, now)
                profiler.record_serving("expired")
                continue
            self._begin_prefill(req, staged, slot, now)

    # -- SLO scheduling (mxtpu.sched; every method below is sched-mode only) --
    def _admit_sched(self, wait_s: float) -> None:
        """Sched-mode admission: pull EVERY staged request into the pending
        pool, then let the policy decide — shed the doomed, resume parked
        requests into free slots, preempt a lower tier for a waiting higher
        one, and start (batched) prefill on the fair-share winner(s)."""
        while self._feed is not None:
            try:
                item = self._feed.poll(timeout=wait_s)
            except StopIteration:
                break
            if item is None:
                break
            wait_s = 0.0
            self._sched.register(item[0])
            self._sched_pending.append(item)
        now = time.monotonic()
        keep = []
        for req, staged in self._sched_pending:
            if req._cancelled():
                self._finish_unslotted(req, CANCELLED, now)
            elif req._expired(now):
                self._finish_unslotted(req, EXPIRED, now)
            else:
                keep.append((req, staged))
        self._sched_pending = keep
        self._resume_parked(now)
        if self._pf is not None or self._pfg is not None \
                or not self._sched_pending:
            return
        choice, shed = self._sched.select(
            [r for r, _ in self._sched_pending], now)
        self._apply_shed(shed, now)
        if choice is None:
            return
        slot = self._free_slot()
        if slot is None:
            slot = self._preempt_for(choice, now)
            if slot is None:
                return                    # saturated; wait for a retire
        self._sched.charge(choice)        # slot secured: commit the pick
        if self._prefill_batch > 1 and len(self._sched_pending) > 1:
            self._begin_group(choice, slot, now)
        else:
            staged = self._pop_pending(choice)
            self._begin_prefill(choice, staged, slot, now)

    def _pop_pending(self, req):
        for i, (r, _s) in enumerate(self._sched_pending):
            if r.id == req.id:
                return self._sched_pending.pop(i)[1]
        raise KeyError(req.id)     # unreachable: select() picked from pending

    def _finish_unslotted(self, req, state: str, now: float) -> None:
        req._finish(state, now)
        profiler.record_serving({CANCELLED: "cancelled",
                                 EXPIRED: "expired"}[state])
        self._sched.forget(req)

    def _apply_shed(self, shed, now: float) -> None:
        for req in shed:
            req._finish(SHED, now, error=self._sched.shed_error(req, now))
            profiler.record_serving("shed")
            profiler.record_tenant(req.tenant, "shed")
            tracer.instant("serving/shed", cat="serving",
                           args={"id": req.id, "tenant": req.tenant,
                                 "priority": req.priority})
            self._sched.forget(req)
        if shed:
            gone = {r.id for r in shed}
            self._sched_pending = [(r, s) for r, s in self._sched_pending
                                   if r.id not in gone]
            profiler.record_sched(self._sched.stats())

    def _preempt_for(self, incoming, now: float) -> Optional[int]:
        """Park a lower-tier running request so ``incoming`` gets its
        decode slot; returns the freed slot (None: nobody preemptible)."""
        running = [self._reqs[int(s)] for s in np.flatnonzero(self._active)]
        victim = self._sched.pick_victim(running, incoming)
        if victim is None:
            return None
        slot = next(i for i, r in enumerate(self._reqs)
                    if r is not None and r.id == victim.id)
        self._park(slot, now)
        return slot

    def _park(self, slot: int, now: float) -> None:
        """Freeze a running request out of its decode slot — exactly the
        state a drain() entry carries (kept device-resident) — and queue
        it for :meth:`_resume_parked`. The page plus (tok, p, limit)
        cursors ARE the decode chain, so resume is bit-exact for the same
        reason adopt() is."""
        req = self._reqs[slot]
        entry = {
            "req": req, "tot": self._TOT,
            "page": kv.slot_page(self._caches, slot),
            "tok": int(self._tok[slot]), "p": int(self._p[slot]),
            "limit": int(self._limit[slot]), "left": int(self._left[slot]),
            "temp": float(self._temp[slot]), "topk": int(self._topk[slot]),
            "seed": int(self._seed[slot]),
            "dec_emitted": bool(self._dec_emitted[slot]),
        }
        if self._spec is not None:
            # in-flight drafts park with the slot (pure proposals — no K/V
            # committed for them yet) and resume where they left off
            entry["draft"] = self._draft[slot].tolist()
            entry["dlen"] = int(self._dlen[slot])
            self._dlen[slot] = 0
        self._parked.append(entry)
        req._set_state(PENDING)
        self._sched.note_preempt()
        profiler.record_serving("preempted")
        profiler.record_tenant(req.tenant, "preempted")
        tracer.instant("serving/preempt", cat="serving",
                       args={"id": req.id, "slot": slot,
                             "p": int(self._p[slot]), "tenant": req.tenant,
                             "priority": req.priority})
        self._reqs[slot] = None
        self._active[slot] = False
        self._tok[slot] = 0
        self._p[slot] = 0
        self._limit[slot] = 0
        self._left[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._seed[slot] = 0
        self._dec_emitted[slot] = False

    def _resume_parked(self, now: float) -> None:
        """Re-slot parked requests (FIFO) while slots are free — unless a
        pending request outranks the parked one, in which case the free
        slot is left for admission (don't hand the slot straight back to
        the tier that just lost it)."""
        while self._parked:
            slot = self._free_slot()
            if slot is None:
                return
            e = self._parked[0]
            req = e["req"]
            if req._cancelled() or req._expired(now):
                self._parked.pop(0)
                self._finish_unslotted(
                    req, CANCELLED if req._cancelled() else EXPIRED, now)
                continue
            my_rank = self._sched.tier(req).rank
            if any(self._sched.tier(r).rank < my_rank
                   for r, _ in self._sched_pending):
                return
            self._parked.pop(0)
            page = kv.device_page(e["page"])
            self._ensure_capacity(e["tot"])
            if e["tot"] < self._TOT:
                page = kv.promote(page, self._TOT)
            self._merge_page(page, slot)
            self._tok[slot] = e["tok"]
            self._p[slot] = e["p"]
            self._limit[slot] = e["limit"]
            self._left[slot] = e["left"]
            self._temp[slot] = e["temp"]
            self._topk[slot] = e["topk"]
            self._seed[slot] = e["seed"]
            self._t_admit[slot] = now
            self._dec_emitted[slot] = e["dec_emitted"]
            if self._spec is not None and e.get("dlen"):
                n = min(int(e["dlen"]), self._spec.k)
                self._draft[slot, :n] = e["draft"][:n]
                self._dlen[slot] = n
            self._active[slot] = True
            self._reqs[slot] = req
            req._set_state(RUNNING)
            self._sched.note_resume()
            profiler.record_serving("resumed")
            tracer.instant("serving/resume", cat="serving",
                           args={"id": req.id, "slot": slot, "p": e["p"],
                                 "tenant": req.tenant})

    def _begin_group(self, first, first_slot: int, now: float) -> None:
        """Collect up to ``prefill_batch`` fair-share winners (bounded by
        free slots) and start ONE batched prefill over their packed
        prompts (``mxtpu.sched.admission``)."""
        picked = [(first, self._pop_pending(first), first_slot)]
        taken = {first_slot}
        while len(picked) < self._prefill_batch and self._sched_pending:
            slot = self._free_slot(exclude=taken)
            if slot is None:
                break
            choice, shed = self._sched.select(
                [r for r, _ in self._sched_pending], now)
            self._apply_shed(shed, now)
            if choice is None:
                break
            self._sched.charge(choice)    # joins the group: slot reserved
            picked.append((choice, self._pop_pending(choice), slot))
            taken.add(slot)
        if len(picked) == 1:
            self._begin_prefill(first, picked[0][1], first_slot, now)
            return
        from ..sched.admission import PrefillGroup
        PB = max(s.shape[1] for _, s, _ in picked)
        members = []
        for req, staged, slot in picked:
            t0 = len(req.prompt)
            req._set_state(RUNNING)
            profiler.record_serving("admitted")
            profiler.record_serving("queue_wait_ms_last",
                                    (now - req.t_submit) * 1e3)
            tracer.instant("serving/admit", cat="serving",
                           args={"id": req.id, "slot": slot,
                                 "tenant": req.tenant,
                                 "queue_wait_ms": round(
                                     (now - req.t_submit) * 1e3, 3)})
            m, blocks = 0, None
            if self._prefix is not None and req.use_prefix_cache \
                    and t0 - 1 >= kv.PrefixCache.BLOCK:
                m, blocks, path = self._prefix.match(req.prompt, t0 - 1)
                # the pins only guard the tree nodes; the block arrays stay
                # alive through `blocks` itself, so release before install
                # is safe here (PrefillGroup installs them immediately)
                self._prefix.release(path)
                self._note_prefix_probe(req, m)
            temp, topk, seed = _req_sampling(req)
            members.append({"req": req, "slot": slot, "t0": t0,
                            "start": m, "blocks": blocks or None,
                            "left": req.max_new, "done": False,
                            "t_start": now, "temp": temp, "topk": topk,
                            "seed": seed})
        self._pfg = PrefillGroup(self._model, members, self._prefill_batch,
                                 PB, self._kv_dtype, self._quant)
        # the group page must join the mesh's device set before the first
        # batched-prefill dispatch (the slot dim shards when divisible,
        # heads on tp — same filter path as the full cache)
        self._pfg.page = self._place_caches(self._pfg.page)
        profiler.record_serving("prefill_groups")
        tracer.instant("serving/prefill_group", cat="serving",
                       args={"ids": [mm["req"].id for mm in members],
                             "bucket": PB, "rows": len(members)})

    def _prefill_group_chunk(self) -> None:
        """Advance the batched prefill by ONE fixed-budget chunk (the same
        stall bound as the scalar path — one chunk's work per turn, shared
        by all members); emit each member's valid tokens, finish members
        that complete at admission, and at scan end merge every survivor
        into its reserved slot."""
        g = self._pfg
        now = time.monotonic()
        for mem in g.members:
            req = mem["req"]
            if mem["done"]:
                continue
            if req._cancelled():
                mem["done"] = True
                self._finish_unslotted(req, CANCELLED, now)
            elif req._expired(now):
                mem["done"] = True
                self._finish_unslotted(req, EXPIRED, now)
        if all(m["done"] for m in g.members):
            self._pfg = None
            return
        csize = min(self.prefill_chunk, g.remaining())
        live_ids = [m["req"].id for m in g.members if not m["done"]]
        with tracer.span("serving/prefill_chunk", cat="serving",
                         args={"ids": live_ids, "start": g.cursor,
                               "chunk": csize, "bucket": g.PB,
                               "batched": len(live_ids)}):
            from ..sched.admission import build_prefill_batch
            with self._scope():
                fn = self._prefill_fns.get_or_build(
                    ("batch", g.N, g.PB, csize),
                    lambda: build_prefill_batch(
                        self._model, g.N, g.PB, csize, quant=self._quant,
                        decode_kernel=self._decode_kernel))
                page, prev, lastfed, outs = fn(
                    self._params,
                    *(inp if i == 0 else self._dev(inp)
                      for i, inp in enumerate(g.chunk_inputs())))
            outs_np = np.asarray(outs)
        profiler.record_serving("prefill_chunks")
        self._sched.observe_prefill(csize * len(live_ids),
                                    time.monotonic() - now)
        for n, mem in enumerate(g.members):
            if mem["done"]:
                continue
            req = mem["req"]
            j_lo, j_hi = g.valid_range(n, csize)
            if j_lo >= j_hi:
                continue
            valid = outs_np[j_lo:j_hi, n]
            done_t = time.monotonic()
            first = req.t_first_token is None
            left = req._emit(valid.tolist(), done_t)
            profiler.record_serving("tokens_out", mem["left"] - left)
            self._sched.charge_tokens(req.tenant, mem["left"] - left)
            mem["left"] = left
            if first:
                self._note_first_token(req, done_t, mem["t_start"])
            if left == 0:
                # short request: completed inside the group, never decodes.
                # NB: slice the chunk's OUTPUT page — g.page is pre-advance
                # here (advance runs after this loop), and inserting the
                # stale rows would seed the prefix tree with blocks the
                # scan hasn't written yet
                mem["done"] = True
                self._insert_prefix(req, kv.slot_page(page, n),
                                    upto=g.cursor + csize)
                req._finish(DONE, done_t)
                profiler.record_serving("prefills")
                profiler.record_serving("completed")
                profiler.record_tenant(req.tenant, "completed")
                profiler.record_tenant(req.tenant, "goodput_tokens",
                                       req.max_new)
                self._sched.forget(req)
                tracer.instant("serving/retire", cat="serving",
                               args={"id": req.id, "state": DONE,
                                     "tenant": req.tenant,
                                     "at_admission": True})
        g.advance(page, prev, lastfed, csize)
        if g.remaining() == 0:
            self._finish_group()
        profiler.record_sched(self._sched.stats())

    def _finish_group(self) -> None:
        """Batched-prefill phase three: every member row is scanned to the
        bucket end — merge each survivor's page row into its reserved slot
        and hand it to the decode batch (the groupwise twin of
        :meth:`_finish_prefill`)."""
        g, self._pfg = self._pfg, None
        prev_np = np.asarray(g.prev)
        now = time.monotonic()
        survivors = [(n, m) for n, m in enumerate(g.members)
                     if not m["done"]]
        if not survivors:
            return
        need = max([g.PB] + [kv.bucket32(m["req"].total,
                                         self._model._max_len)
                             for _n, m in survivors])
        self._ensure_capacity(need)
        for n, mem in survivors:
            req = mem["req"]
            slot = mem["slot"]
            self._insert_prefix(req, g.member_page(n), upto=mem["t0"] - 1)
            self._merge_page(g.member_page(n), slot)
            self._tok[slot] = int(prev_np[n])    # the token at position PB
            self._p[slot] = g.PB                 # next position to feed
            self._limit[slot] = req.total - 1
            self._active[slot] = True
            self._left[slot] = mem["left"]
            self._temp[slot] = mem["temp"]
            self._topk[slot] = mem["topk"]
            self._seed[slot] = mem["seed"]
            self._t_admit[slot] = now
            self._dec_emitted[slot] = False
            self._reqs[slot] = req
            profiler.record_serving("prefills")

    def _note_prefix_probe(self, req, m: int) -> None:
        """Prefix-probe accounting shared by scalar and group admission
        (partial-block hits count the sub-block tail separately)."""
        if m:
            profiler.record_serving("prefix_hits")
            profiler.record_serving("prefix_hit_tokens", m)
            if m % kv.PrefixCache.BLOCK:
                profiler.record_serving("prefix_partial_hits")
                profiler.record_serving("prefix_partial_tokens",
                                        m % kv.PrefixCache.BLOCK)
            tracer.instant("serving/prefix_hit", cat="serving",
                           args={"id": req.id, "tokens": m})
        else:
            profiler.record_serving("prefix_misses")
            tracer.instant("serving/prefix_miss", cat="serving",
                           args={"id": req.id})

    def _note_first_token(self, req, done_t: float,
                          t_start: float) -> None:
        profiler.record_serving("ttft_ms_last",
                                (done_t - req.t_submit) * 1e3)
        profiler.record_serving("prefill_ms_last",
                                (done_t - t_start) * 1e3)
        if self._sched is not None:
            profiler.record_tenant(req.tenant, "ttft_ms_last",
                                   (done_t - req.t_submit) * 1e3)
        tracer.instant("serving/first_token", cat="serving",
                       args={"id": req.id,
                             "ttft_ms": round(
                                 (done_t - req.t_submit) * 1e3, 3)})

    def _begin_prefill(self, req: ServingRequest, staged, slot: int,
                       now: float) -> None:
        """Admission, phase one: probe the radix prefix cache, seed the
        page with any cached rows, and park the partial-prefill cursor at
        the first position that still needs computing."""
        t0 = len(req.prompt)
        PB = staged.shape[1]
        req._set_state(RUNNING)
        profiler.record_serving("admitted")
        profiler.record_serving("queue_wait_ms_last",
                                (now - req.t_submit) * 1e3)
        tracer.instant("serving/admit", cat="serving",
                       args={"id": req.id, "slot": slot,
                             "queue_wait_ms": round(
                                 (now - req.t_submit) * 1e3, 3)})
        page = kv.empty_page(self._model, PB, self._kv_dtype, self._quant)
        m = 0
        # only FORCED prompt positions are reusable (limit = t0 - 1: the
        # last prompt position seeds the feedback chain and is recomputed)
        if self._prefix is not None and req.use_prefix_cache \
                and t0 - 1 >= kv.PrefixCache.BLOCK:
            m, blocks, path = self._prefix.match(req.prompt, t0 - 1)
            if m:
                # COPY the cached rows into this request's page (functional
                # .at[].set — the tree's rows are never aliased mutably;
                # quantized blocks install their bytes, never re-quantize)
                page = kv.install_rows(page, blocks, m)
                self._prefix.release(path)
            self._note_prefix_probe(req, m)
        temp, topk, seed = _req_sampling(req)
        # scan from the last BLOCK boundary, not the raw match length: a
        # partial-block hit (m % 32 != 0) re-feeds its sub-block tail as an
        # identical rewrite (K/V at p is a pure function of tokens 0..p),
        # which keeps the (PB, csize) program-key space bounded — an
        # arbitrary mid-block cursor would mint a fresh multi-second XLA
        # compile per distinct tail length
        t_scan = m - (m % kv.PrefixCache.BLOCK)
        # mesh mode: the fresh page must live on the mesh's device set
        # before it rides a dispatch next to the placed params (jnp-created
        # arrays are committed to the default device)
        page = self._place_caches(page)
        self._pf = {"req": req, "prompt": staged.data, "page": page,
                    "t": t_scan, "prev": 0, "t0": t0, "PB": PB,
                    "left": req.max_new, "slot": slot, "t_start": now,
                    "temp": temp, "topk": topk, "seed": seed}

    def _prefill_chunk(self) -> None:
        """Admission, phase two (repeated): advance the partial prefill by
        ONE fixed-budget chunk, emitting any tokens past ``t0`` as they
        materialize; on reaching the bucket end, merge the page into the
        reserved slot and activate it for decode."""
        pf = self._pf
        req = pf["req"]
        now = time.monotonic()
        if req._cancelled():
            self._pf = None
            req._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
            return
        if req._expired(now):
            self._pf = None
            req._finish(EXPIRED, now)
            profiler.record_serving("expired")
            return
        start = pf["t"]
        csize = min(self.prefill_chunk, pf["PB"] - start)
        with tracer.span("serving/prefill_chunk", cat="serving",
                         args={"id": req.id, "start": start,
                               "chunk": csize, "bucket": pf["PB"]}):
            with self._scope():
                fn = self._prefill_fns.get_or_build(
                    (pf["PB"], csize),
                    lambda: kv.build_prefill_chunk(
                        self._model, pf["PB"], csize, quant=self._quant,
                        decode_kernel=self._decode_kernel))
                page, outs = fn(
                    self._params, pf["page"], self._dev(pf["prompt"]),
                    self._dev(jnp.int32(pf["t0"])),
                    self._dev(jnp.int32(start)),
                    self._dev(jnp.full((1,), pf["prev"], jnp.int32)),
                    self._dev(jnp.full((1,), pf["temp"], jnp.float32)),
                    self._dev(jnp.full((1,), pf["topk"], jnp.int32)),
                    self._dev(jnp.full((1,), pf["seed"], jnp.uint32)))
            outs_np = np.asarray(outs)
        profiler.record_serving("prefill_chunks")
        if self._sched is not None:
            # scalar prefills must feed the rate EWMA too, or a sched-mode
            # engine with prefill_batch=1 never warms its shed estimator
            self._sched.observe_prefill(csize, time.monotonic() - now)
        pf["page"] = page
        pf["t"] = start + csize
        pf["prev"] = int(outs_np[-1])
        # outs[j] is the token FOR position start+j+1; generated tokens are
        # positions >= t0, i.e. indices j >= t0-1-start (see kv.py)
        valid = outs_np[max(pf["t0"] - 1 - start, 0):]
        if valid.size:
            done_t = time.monotonic()
            first = req.t_first_token is None
            left = req._emit(valid.tolist(), done_t)
            profiler.record_serving("tokens_out", pf["left"] - left)
            if self._sched is not None:
                self._sched.charge_tokens(req.tenant, pf["left"] - left)
            pf["left"] = left
            if first:
                self._note_first_token(req, done_t, pf["t_start"])
            if left == 0:
                # short request: completed at admission, never took a slot
                self._pf = None
                self._insert_prefix(req, page, upto=pf["t"])
                req._finish(DONE, done_t)
                profiler.record_serving("prefills")
                profiler.record_serving("completed")
                if self._sched is not None:
                    profiler.record_tenant(req.tenant, "completed")
                    profiler.record_tenant(req.tenant, "goodput_tokens",
                                           req.max_new)
                    self._sched.forget(req)
                # terminal timeline marker: every request's timeline ends in
                # a retire even when it never occupied a decode slot
                tracer.instant("serving/retire", cat="serving",
                               args={"id": req.id, "state": DONE,
                                     "at_admission": True})
                return
        if pf["t"] >= pf["PB"]:
            self._finish_prefill(pf)

    def _finish_prefill(self, pf: dict) -> None:
        """Admission, phase three: the whole bucket is prefilled — merge
        the page into the reserved slot row and hand the request to the
        decode batch."""
        req = pf["req"]
        slot = pf["slot"]
        self._pf = None
        self._insert_prefix(req, pf["page"], upto=pf["t0"] - 1)
        self._ensure_capacity(
            kv.bucket32(req.total, self._model._max_len))
        self._merge_page(pf["page"], slot)
        self._tok[slot] = pf["prev"]         # the token at position PB
        self._p[slot] = pf["PB"]             # next position to feed
        self._limit[slot] = req.total - 1
        self._active[slot] = True
        self._left[slot] = pf["left"]
        self._temp[slot] = pf["temp"]
        self._topk[slot] = pf["topk"]
        self._seed[slot] = pf["seed"]
        self._t_admit[slot] = time.monotonic()
        self._dec_emitted[slot] = False
        self._reqs[slot] = req
        profiler.record_serving("prefills")

    def _insert_prefix(self, req: ServingRequest, page, upto: int) -> None:
        """Seed the radix tree with this request's forced-prompt blocks
        (positions below ``upto``, whole 32-blocks only) so the NEXT
        request sharing the prefix skips their prefill."""
        if self._prefix is None or not req.use_prefix_cache:
            return
        created = self._prefix.insert(req.prompt, page,
                                      min(upto, len(req.prompt) - 1))
        if created:
            profiler.record_serving("prefix_inserts", created)
        if self._prefix.evictions > self._evict_seen:
            profiler.record_serving("prefix_evictions",
                                    self._prefix.evictions - self._evict_seen)
            self._evict_seen = self._prefix.evictions
        profiler.record_serving("prefix_cache_bytes", self._prefix.bytes)

    def _ensure_capacity(self, need: int) -> None:
        if self._TOT is None:
            self._TOT = need
            self._caches = self._place_caches(
                kv.empty_cache(self._model, self.slots, need,
                               self._kv_dtype, self._quant))
        elif need > self._TOT:
            with tracer.span("serving/kv_promote", cat="serving",
                             args={"from": self._TOT, "to": need}):
                self._caches = self._place_caches(
                    kv.promote(self._caches, need))
            self._TOT = need
            profiler.record_serving("kv_promotions")
        else:
            return
        profiler.record_serving("kv_bytes_resident",
                                kv.cache_nbytes(self._caches))

    # -- sharded placement (mesh mode; all identity when mesh is None) -------
    def _place_caches(self, caches):
        """Pin a freshly created / promoted / page-merged cache onto the
        canonical kv_cache sharding so dispatch-input shardings never drift
        from what the first trace keyed on (trace-once over shardings)."""
        if self._mesh is None:
            return caches
        from . import sharded
        return sharded.place_cache(caches, self._mesh, self._layout)

    def _merge_page(self, page, slot: int) -> None:
        """``kv.merge_page`` + re-pin: every eager host-side cache mutation
        funnels through here in mesh mode. The incoming page is placed
        FIRST — a parked/adopted page arrives committed to the default
        device, and an eager merge across mismatched device sets throws."""
        page = self._place_caches(page)
        self._caches = self._place_caches(
            kv.merge_page(self._caches, page, slot))

    def _scope(self):
        """Layout scope for program dispatch: under a mesh every dispatch
        (and therefore every first-call trace) runs with the serving layout
        active, so the step functions' activation constraints fire."""
        if self._mesh is None:
            return nullcontext()
        from ..parallel.fsdp import layout_scope
        return layout_scope(self._layout, self._mesh)

    def _dev(self, x):
        """Replicate a small dispatch input (slot-state vectors, prompt
        block, cursors) onto the mesh's device set. jnp-created arrays are
        committed to the default device, and a jit mixing them with the
        mesh-placed params throws; replicating through ONE NamedSharding
        also keeps the dispatch-input shardings identical across calls
        (trace-once)."""
        if self._mesh is None:
            return x
        import jax
        from ..parallel.mesh import NamedSharding, P
        return jax.device_put(x, NamedSharding(self._mesh, P()))

    def _decode_chunk(self) -> None:
        n_active = int(self._active.sum())
        span_args = {"active": n_active, "tot": self._TOT}
        if tracer.enabled():
            # tag the dispatch with the whole slot batch's request ids so
            # request_timeline()/per-request lanes can claim it (built only
            # under tracing — the off path stays a dict literal)
            span_args["ids"] = [self._reqs[int(s)].id
                                for s in np.flatnonzero(self._active)]
        t_dispatch = time.monotonic()
        with tracer.span("serving/decode", cat="serving", args=span_args):
            key = (self.slots, self._TOT, self.chunk)
            with self._scope():
                fn = self._decode_fns.get_or_build(
                    key, lambda: kv.build_decode(
                        self._model, *key, quant=self._quant,
                        decode_kernel=self._decode_kernel))
                caches, tok, p, toks, lives = fn(
                    self._params, self._caches,
                    self._dev(jnp.asarray(self._tok)),
                    self._dev(jnp.asarray(self._p)),
                    self._dev(jnp.asarray(self._active)),
                    self._dev(jnp.asarray(self._limit)),
                    self._dev(jnp.asarray(self._temp)),
                    self._dev(jnp.asarray(self._topk)),
                    self._dev(jnp.asarray(self._seed)))
            toks_np = np.asarray(toks)
            lives_np = np.asarray(lives)
        self._caches = caches
        self._tok = np.array(tok)   # owned copies: the slot state is
        self._p = np.array(p)       # mutated at retire/admit boundaries
        now = time.monotonic()
        profiler.record_serving("decode_steps")
        # re-assert per dispatch: these are assign-style stats, and callers
        # commonly reset_serving_stats() after warmup (which wiped the values
        # recorded at start()/cache creation)
        profiler.record_serving("engine", self.engine_id)
        profiler.record_serving("kv_dtype", self._kv_dtype_str)
        if self._decode_kernel_str is not None:
            profiler.record_serving("decode_kernel", self._decode_kernel_str)
        profiler.record_serving("kv_bytes_resident",
                                kv.cache_nbytes(self._caches))
        profiler.record_serving_occupancy(n_active, self.slots)
        emitted_total = 0
        for slot in np.flatnonzero(self._active):
            req = self._reqs[slot]
            fresh = toks_np[lives_np[:, slot], slot]
            if fresh.size:
                left = req._emit(fresh.tolist(), now)
                got = int(self._left[slot] - left)
                profiler.record_serving("tokens_out", got)
                emitted_total += got
                self._left[slot] = left
                if self._sched is not None:
                    self._sched.charge_tokens(req.tenant, got)
                if not self._dec_emitted[slot]:
                    self._dec_emitted[slot] = True
                    profiler.record_serving(
                        "first_decode_ms_last",
                        (now - self._t_admit[slot]) * 1e3)
                    tracer.instant("serving/first_decode", cat="serving",
                                   args={"id": req.id})
            if self._left[slot] == 0:
                self._retire(slot, DONE, now)
            elif req._cancelled():
                self._retire(slot, CANCELLED, now)
            elif req._expired(now):
                self._retire(slot, EXPIRED, now)
        if emitted_total:
            # dispatch wall clock amortized per emitted token — one sample
            # per dispatch into the serving/token_ms histogram
            profiler.record_serving(
                "token_ms_last", (now - t_dispatch) * 1e3 / emitted_total)
            # decode-only throughput series: full dispatch wall + its token
            # yield, so decode_tokens / decode_ms_total excludes prefill and
            # scheduler time (the quant_decode_speedup denominator)
            profiler.record_serving("decode_ms_last",
                                    (now - t_dispatch) * 1e3)
            profiler.record_serving("decode_tokens", emitted_total)
        if self._sched is not None:
            if emitted_total:
                self._sched.observe_decode(emitted_total, now - t_dispatch)
            profiler.record_sched(self._sched.stats())

    # -- speculative decode (mxtpu.serving.spec; spec-mode only below) -------
    def _spec_decode_turn(self) -> None:
        """One decode turn under speculation: dispatch the verify program
        when any slot holds drafts (a slot without them runs a plain
        single-position step INSIDE the same program — no retrace), fall
        back to the ordinary decode chunk when nobody does (a cold or
        miss-everywhere turn keeps plain-chunk throughput), then propose
        the NEXT turn's drafts from each survivor's updated stream. The
        end-of-turn proposal order is what makes a drain() between turns
        carry genuine in-flight drafts."""
        if int(self._dlen.sum()) > 0:
            self._verify_chunk()
        else:
            self._decode_chunk()
        self._propose_drafts()

    def _propose_drafts(self) -> None:
        """Refill the per-slot draft buffers for the next dispatch. Greedy
        slots only — a sampled slot's next token is a draw, not an argmax,
        so speculation degrades it to dlen=0 plain decode per slot (the
        verify program re-checks ``temp`` on-device as well). Proposals
        are clipped to the slot's remaining live positions; the final
        token of a request always decodes plain."""
        k = self._spec.k
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            self._dlen[slot] = 0
            if self._temp[slot] > 0:
                continue
            room = int(self._limit[slot]) - int(self._p[slot]) - 1
            if room <= 0:
                continue
            req = self._reqs[slot]
            prop = self._drafter.propose(req.prompt + req.tokens(),
                                         min(k, room))
            n = min(len(prop), k, room)
            if n > 0:
                self._draft[slot, :n] = prop[:n]
                self._dlen[slot] = n
                profiler.record_serving("tokens_drafted", n)
        self._publish_ngram_stats()

    def _publish_ngram_stats(self) -> None:
        """Mirror the PrefixCache's n-gram lookup counters into the serving
        stats as deltas (same idiom as prefix_evictions)."""
        if self._prefix is None:
            return
        dh = self._prefix.ngram_hits - self._ngram_hits_seen
        dm = self._prefix.ngram_misses - self._ngram_misses_seen
        if dh:
            profiler.record_serving("ngram_hits", dh)
        if dm:
            profiler.record_serving("ngram_misses", dm)
        self._ngram_hits_seen = self._prefix.ngram_hits
        self._ngram_misses_seen = self._prefix.ngram_misses

    def _verify_chunk(self) -> None:
        """Dispatch ONE batched verify: all k+1 positions of every slot
        scored by a single target forward, greedy accept/reject on-device,
        then exactly one host readback of (outs, lives) — the sanctioned
        readback tpulint R009 polices; per-token ``.item()`` loops here
        would serialize a device sync per accepted token."""
        k = self._spec.k
        n_active = int(self._active.sum())
        span_args = {"active": n_active, "tot": self._TOT, "k": k}
        if tracer.enabled():
            span_args["ids"] = [self._reqs[int(s)].id
                                for s in np.flatnonzero(self._active)]
        t_dispatch = time.monotonic()
        with tracer.span("serving/verify", cat="serving", args=span_args):
            key = (self.slots, self._TOT, k)
            with self._scope():
                fn = self._verify_fns.get_or_build(
                    key, lambda: kv.build_verify(
                        self._model, *key, quant=self._quant,
                        decode_kernel=self._decode_kernel))
                caches, tok, p, outs, lives = fn(
                    self._params, self._caches,
                    self._dev(jnp.asarray(self._tok)),
                    self._dev(jnp.asarray(self._p)),
                    self._dev(jnp.asarray(self._active)),
                    self._dev(jnp.asarray(self._limit)),
                    self._dev(jnp.asarray(self._temp)),
                    self._dev(jnp.asarray(self._topk)),
                    self._dev(jnp.asarray(self._seed)),
                    self._dev(jnp.asarray(self._draft)),
                    self._dev(jnp.asarray(self._dlen)))
            outs_np = np.asarray(outs)
            lives_np = np.asarray(lives)
        self._caches = caches
        self._tok = np.array(tok)
        self._p = np.array(p)
        now = time.monotonic()
        profiler.record_serving("decode_steps")
        profiler.record_serving("spec_dispatches")
        profiler.record_serving("engine", self.engine_id)
        profiler.record_serving("kv_dtype", self._kv_dtype_str)
        if self._decode_kernel_str is not None:
            profiler.record_serving("decode_kernel", self._decode_kernel_str)
        profiler.record_serving("kv_bytes_resident",
                                kv.cache_nbytes(self._caches))
        profiler.record_serving_occupancy(n_active, self.slots)
        emitted_total = 0
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            req = self._reqs[slot]
            fresh = outs_np[slot, lives_np[slot]]
            drafted = int(self._dlen[slot])
            self._dlen[slot] = 0          # consumed, hit or miss
            if fresh.size:
                left = req._emit(fresh.tolist(), now)
                got = int(self._left[slot] - left)
                profiler.record_serving("tokens_out", got)
                emitted_total += got
                self._left[slot] = left
                if self._sched is not None:
                    self._sched.charge_tokens(req.tenant, got)
                # accept-length sample: tokens this slot emitted from one
                # dispatch (1 = no speculation win, k+1 = full accept)
                e = int(fresh.size)
                profiler.record_serving("accept_len_last", e)
                confirmed = min(max(e - 1, 0), drafted)
                if confirmed:
                    profiler.record_serving("tokens_accepted", confirmed)
                if drafted - confirmed:
                    profiler.record_serving("tokens_rejected",
                                            drafted - confirmed)
                if not self._dec_emitted[slot]:
                    self._dec_emitted[slot] = True
                    profiler.record_serving(
                        "first_decode_ms_last",
                        (now - self._t_admit[slot]) * 1e3)
                    tracer.instant("serving/first_decode", cat="serving",
                                   args={"id": req.id})
            if self._left[slot] == 0:
                self._retire(slot, DONE, now)
            elif req._cancelled():
                self._retire(slot, CANCELLED, now)
            elif req._expired(now):
                self._retire(slot, EXPIRED, now)
        if emitted_total:
            profiler.record_serving(
                "token_ms_last", (now - t_dispatch) * 1e3 / emitted_total)
            profiler.record_serving("decode_ms_last",
                                    (now - t_dispatch) * 1e3)
            profiler.record_serving("decode_tokens", emitted_total)
        if self._sched is not None:
            if emitted_total:
                self._sched.observe_decode(emitted_total, now - t_dispatch)
            profiler.record_sched(self._sched.stats())

    def _retire(self, slot: int, state: str, now: float) -> None:
        req = self._reqs[slot]
        req._finish(state, now)
        profiler.record_serving({DONE: "completed", CANCELLED: "cancelled",
                                 EXPIRED: "expired"}[state])
        if self._sched is not None:
            self._sched.forget(req)
            profiler.record_tenant(
                req.tenant, {DONE: "completed", CANCELLED: "cancelled",
                             EXPIRED: "expired"}[state])
            if state == DONE:
                profiler.record_tenant(req.tenant, "goodput_tokens",
                                       len(req.tokens()))
        tracer.instant("serving/retire", cat="serving",
                       args={"id": req.id, "state": state})
        self._reqs[slot] = None
        self._active[slot] = False
        self._tok[slot] = 0
        self._p[slot] = 0
        self._limit[slot] = 0
        self._left[slot] = 0
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._seed[slot] = 0
        self._dec_emitted[slot] = False
        if self._spec is not None:
            self._dlen[slot] = 0

    def _maybe_log(self) -> None:
        """Per-interval engine log (``MXTPU_SERVING_LOG_S``): one line with
        the TTFT decomposition and cache/occupancy health."""
        if not self._log_s:
            return
        now = time.monotonic()
        if now < self._next_log:
            return
        self._next_log = now + self._log_s
        s = profiler.get_serving_stats()
        _log.info(
            "serving: %d in-flight / %d done; ttft last %.1f ms "
            "(queue %.1f + prefill %.1f), first-decode %.1f ms; "
            "occupancy %.2f; prefix hit-rate %.2f (%d hits, %.1f MB)",
            int(self._active.sum()) + (1 if self._pf is not None else 0),
            s["completed"], s["ttft_ms_last"], s["queue_wait_ms_last"],
            s["prefill_ms_last"], s["first_decode_ms_last"],
            s["slot_occupancy"], s["prefix_hit_rate"], s["prefix_hits"],
            s["prefix_cache_bytes"] / (1 << 20))

    def _shutdown_sweep(self) -> None:
        """Terminal sweep: nothing submitted may block forever — in-slot,
        mid-prefill, staged, and still-queued requests all finish
        CANCELLED."""
        self._stop.set()     # scheduler may exit via error with stop unset
        now = time.monotonic()
        for slot in np.flatnonzero(self._active):
            self._retire(int(slot), CANCELLED, now)
        if self._pf is not None:
            pf, self._pf = self._pf, None
            pf["req"]._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
        if self._pfg is not None:
            g, self._pfg = self._pfg, None
            for mem in g.members:
                if not mem["done"]:
                    mem["req"]._finish(CANCELLED, now)
                    profiler.record_serving("cancelled")
        for e in self._parked:
            e["req"]._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
        self._parked = []
        for req, _s in self._sched_pending:
            req._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
        self._sched_pending = []
        # staged by the feed but never admitted: drain until the producer's
        # end marker (it sees the stop flag within its 0.1s poll)
        deadline = time.monotonic() + 5.0
        while self._feed is not None and time.monotonic() < deadline:
            try:
                item = self._feed.poll(timeout=0.2)
            except StopIteration:
                break
            except Exception:   # producer died mid-teardown: nothing to drain
                break
            if item is None:
                continue
            item[0]._finish(CANCELLED, now)
            profiler.record_serving("cancelled")
        while True:                    # never even staged
            try:
                req = self._submit_q.get_nowait()
            except queue.Empty:
                break
            req._finish(CANCELLED, now)
            profiler.record_serving("cancelled")


def audit_key_specs(max_len: int, slots: int, chunk: int, prefill_chunk: int,
                    k: int, bucket=None):
    """The live ProgramCache key sites above, as data — the program
    auditor's retrace-closure proof (rule A301).  Each row is ``(name,
    keys_of, component_bounds)``: ``keys_of(prompt_len, total)`` returns
    every program key a request with that geometry can dispatch under
    (prefill returns one key per chunk step), and ``component_bounds[i]``
    caps how many distinct values component ``i`` may take across the
    WHOLE admissible request domain.  The product of the bounds caps the
    program count, which is exactly the trace-once contract: bucketing is
    what closes the key set, so a raw length leaking into a key (the
    seeded ``--expect-fail`` case passes ``bucket=lambda n: n``) blows a
    component's bound and the audit fails before the recompile storm
    ships.  Keep these in lockstep with the ``get_or_build`` tuples in
    ``_dispatch_decode`` / ``_dispatch_verify`` / the two prefill sites."""
    b = bucket or (lambda n: kv.bucket32(n, max_len))
    nb = (max_len + 31) // 32          # distinct 32-token bucket values

    def decode_keys(plen, total):
        return [(slots, b(total), chunk)]

    def verify_keys(plen, total):
        return [(slots, b(total), k)]

    def prefill_keys(plen, total):
        PB = b(plen)
        return [(PB, min(prefill_chunk, PB - s))
                for s in range(0, PB, prefill_chunk)]

    return [
        ("serving_decode", decode_keys, (1, nb, 1)),
        ("serving_verify", verify_keys, (1, nb, 1)),
        ("serving_prefill", prefill_keys, (nb, nb + 1)),
    ]
