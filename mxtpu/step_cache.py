"""Fused training-step executor + framework-wide compile-cache registry.

The reference's headline perf design is the dependency engine bulking many
small pushed ops into few engine ops (``MXNET_ENGINE_BULK_SIZE``,
threaded_engine.h:404) plus CachedOp whole-graph execution. The TPU-native
equivalent of "bulk size = everything" is compiling the ENTIRE training step —
forward, loss, backward, gradient scaling, and optimizer update — into one
XLA program with donated parameter/optimizer-state buffers. That is what
:class:`StepExecutor` does; ``mxtpu.module.Module`` routes
``forward_backward``/``update`` through it whenever the step is fusable, and
``engine.bulk(0)`` / ``engine.set_bulk_size(0)`` is the documented opt-out
that forces the eager per-op path (debugging, Monitor spying).

This module also owns the framework-wide **compile-cache registry**: every
signature cache (CachedOp / StepExecutor / symbol Executor backward /
DataParallelTrainer) registers its hits and traces here, exposed through
``mxtpu.profiler.get_compile_stats()`` — the observability story for "did my
loop retrace?" (the reference's equivalent forensic is engine bulk logging).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["CacheStats", "cache_stats", "snapshot", "reset_stats",
           "ProgramCache", "StepExecutor", "build_update_all",
           "optimizer_fingerprint"]


# ---------------------------------------------------------------------------
# compile-cache registry
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_registry: "Dict[str, CacheStats]" = {}


class CacheStats:
    """Hit/trace counters for one named signature cache.

    ``misses`` counts traces (every compile of a new signature); ``retraces``
    is the number of compiles beyond the first — the "my fixed-shape loop
    recompiled" red flag tests and CI guards key off.
    """

    __slots__ = ("name", "hits", "misses")

    def __init__(self, name: str):
        self.name = name
        self.hits = 0
        self.misses = 0

    def hit(self):
        self.hits += 1

    def miss(self):
        self.misses += 1

    @property
    def traces(self) -> int:
        return self.misses

    @property
    def retraces(self) -> int:
        return max(0, self.misses - 1)

    def as_dict(self) -> dict:
        return {"hits": self.hits, "traces": self.misses,
                "retraces": self.retraces}


def cache_stats(name: str) -> CacheStats:
    """Get-or-create the stats entry for a named cache."""
    with _lock:
        st = _registry.get(name)
        if st is None:
            st = _registry[name] = CacheStats(name)
        return st


def snapshot() -> Dict[str, dict]:
    """All registered caches → {hits, traces, retraces}."""
    with _lock:
        return {name: st.as_dict() for name, st in _registry.items()}


def reset_stats(name: Optional[str] = None):
    """Zero one cache's counters, or all of them (tests, epoch boundaries)."""
    with _lock:
        targets = [_registry[name]] if name in _registry else (
            [] if name is not None else list(_registry.values()))
        for st in targets:
            st.hits = 0
            st.misses = 0


# ---------------------------------------------------------------------------
# bounded signature→program caches (serving-side compile caches)
# ---------------------------------------------------------------------------


def _program_cache_capacity(env: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(env, str(default))))
    except ValueError:
        return default


class ProgramCache:
    """Bounded LRU signature→compiled-program cache, registered in the
    compile-cache registry above.

    ``ChainedPredictor._fns`` and ``TransformerLM._gen_fns`` used to be bare
    dicts: under serving-side shape churn (a new batch shape / prompt bucket
    per stream) they grew without limit AND were invisible to
    ``profiler.get_compile_stats()``. This wrapper bounds them (LRU eviction,
    capacity from ``MXTPU_SERVING_PROGRAM_CACHE``, default 64) and counts
    every hit/trace in the named registry entry, so a retrace-leaking serving
    loop shows up in the same forensics table as the training step."""

    def __init__(self, name: str, capacity: Optional[int] = None,
                 env: str = "MXTPU_SERVING_PROGRAM_CACHE"):
        self.name = name
        self.capacity = capacity if capacity is not None \
            else _program_cache_capacity(env, 64)
        self.evictions = 0
        self._fns: "OrderedDict[Any, Any]" = OrderedDict()
        self._stats = cache_stats(name)

    def __len__(self) -> int:
        return len(self._fns)

    def __contains__(self, key) -> bool:
        return key in self._fns

    def get(self, key):
        """Cache lookup; counts a hit and refreshes LRU order on success."""
        fn = self._fns.get(key)
        if fn is not None:
            self._fns.move_to_end(key)
            self._stats.hit()
        return fn

    def put(self, key, fn):
        """Insert a freshly traced program (counts a trace); evicts the
        least-recently-used entry beyond capacity."""
        self._stats.miss()
        self._fns[key] = fn
        self._fns.move_to_end(key)
        while len(self._fns) > self.capacity:
            self._fns.popitem(last=False)
            self.evictions += 1
        return fn

    def get_or_build(self, key, build):
        fn = self.get(key)
        if fn is None:
            fn = self.put(key, build())
        return fn


# ---------------------------------------------------------------------------
# shared in-trace optimizer application
# ---------------------------------------------------------------------------


def optimizer_fingerprint(opt) -> tuple:
    """Static hyperparameter identity of an optimizer instance.

    Part of every fused-step cache key: scalar hyperparams (momentum, betas,
    eps, …) are baked into the trace by ``_kernel``, so changing one must
    retrace. Dynamic per-step values (lr, wd, rescale_grad, update counts)
    are traced arguments and deliberately excluded.
    """
    dynamic = {"lr", "wd", "rescale_grad", "num_update"}
    items = tuple(sorted(
        (k, v) for k, v in vars(opt).items()
        if isinstance(v, (int, float, bool, str)) and k not in dynamic))
    return (type(opt).__name__, opt.clip_gradient is not None, items)


def build_update_all(opt, lr_mults: Sequence[float], wd_mults: Sequence[float],
                     shardings: Optional[Sequence] = None):
    """One traceable function applying ``opt`` to every parameter.

    Exactly the ``_preprocess_grad`` + ``_kernel`` composition the eager
    ``Optimizer.update`` path jits per parameter (and that the
    ``mx.nd.*_update`` fused ops in ``ndarray/fused_optimizer.py`` wrap) —
    inlined so the whole multi-parameter update fuses into the enclosing
    step program. Shared by :class:`StepExecutor` and
    ``parallel.data_parallel.DataParallelTrainer``.

    ``shardings`` (optional per-param ``NamedSharding`` or None entries)
    constrains each gradient to its param's sharding BEFORE the kernel: for
    fsdp-resident params GSPMD resolves the pending data-axis reduction as an
    explicit per-axis reduce-scatter onto the shard (never a replicated
    all-reduce), and the updated param is constrained back to the same
    resident sharding.

    Returns ``update_all(params, grads, states, lr, wd, rescale, clip, t)``
    → ``(new_params, new_states)``. ``clip`` is ignored unless the optimizer
    has ``clip_gradient`` set (a static variant, like ``_get_jitted``).
    """
    clipped = opt.clip_gradient is not None

    def update_all(params, grads, states, lr, wd, rescale, clip, t):
        new_params: List[Any] = []
        new_states: List[Tuple] = []
        for i, (w, g, st) in enumerate(zip(params, grads, states)):
            dt = w.dtype
            g = g.astype(dt)
            sh = shardings[i] if shardings is not None else None
            if sh is not None:
                g = jax.lax.with_sharding_constraint(g, sh)
            gg = opt._preprocess_grad(g, rescale.astype(dt),
                                      clip.astype(dt) if clipped else None)
            out = opt._kernel(w, gg, lr.astype(dt) * lr_mults[i],
                              wd.astype(dt) * wd_mults[i], t, *st)
            if isinstance(out, tuple):
                new_w, new_st = out[0], tuple(out[1:])
            else:
                new_w, new_st = out, ()
            if sh is not None:
                new_w = jax.lax.with_sharding_constraint(new_w, sh)
            new_params.append(new_w)
            new_states.append(new_st)
        return new_params, new_states

    return update_all


# component names of the StepExecutor._sig tuple, in order — the retrace
# sanitizer uses them to label its signature diff ("params[0].dtype changed")
_SIG_LABELS = ("data", "label", "params", "aux", "opt_states", "grad_req",
               "opt_hyperparams", "zero", "quant")


def quant_step_mode():
    # lazy: mxtpu.quant.train imports ops.nn, which must finish registering
    # before quant resolves — deferring breaks the import cycle
    from .quant.train import quant_step_mode as _mode
    return _mode()


def quant_scope(mode):
    from .quant.train import quant_scope as _scope
    return _scope(mode)


def _sharding_of(raw):
    # sharding participates in the executable's contract (same rationale as
    # CachedOp._shard_key): re-placed arrays must retrace
    return getattr(raw, "sharding", None)


def _arr_sig(raw) -> tuple:
    return (tuple(raw.shape), str(raw.dtype), _sharding_of(raw))


def donation_supported() -> bool:
    """Buffer donation is a real transfer-of-ownership only on accelerator
    backends; on cpu XLA ignores it with a warning, so we skip it there."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def unique_buffers(state: Tuple) -> Tuple:
    """Deep-copy optimizer-state arrays so no two donated leaves alias one
    buffer (freshly created zeros states can share a constant; XLA rejects
    donating the same buffer twice)."""
    def copy(s):
        if not hasattr(s, "dtype"):
            return s
        sh = getattr(s, "sharding", None)
        if sh is not None and getattr(sh, "num_devices", 1) > 1:
            # sharding-preserving copy: jnp.array(copy=True) would gather a
            # NamedSharding-placed slot onto one device
            return s + jnp.zeros((), s.dtype)
        return jnp.array(s, copy=True)
    return tuple(copy(s) for s in state)


# ---------------------------------------------------------------------------
# StepExecutor
# ---------------------------------------------------------------------------


class StepExecutor:
    """Compile forward+loss+backward+optimizer-update into ONE cached program.

    Wraps a Gluon-style ``block``, a ``loss_fn`` (callable on
    ``(outputs[0], label)`` returning per-sample losses), and a
    ``gluon.Trainer`` whose optimizer/state it drives. Each ``step()``:

    * looks up the signature (input/param/state shapes+dtypes+shardings,
      grad_req layout, optimizer hyperparam fingerprint) in the cache;
    * on miss, traces the whole step once (``jax.jit`` with
      ``donate_argnums`` on parameters and optimizer state when the backend
      supports donation) and records a trace in the ``module_step`` registry
      entry;
    * runs the compiled program and writes back parameters, aux (BatchNorm
      moving stats), optimizer state, and parameter gradients — so eager
      introspection (``param.grad()``) and eager/fused interleaving stay
      coherent.

    The gradient written back is the UNSCALED sum-gradient (eager-backward
    parity); rescaling by 1/batch_size happens inside the traced update,
    exactly where ``Trainer.step`` applies ``rescale_grad``.
    """

    def __init__(self, block, loss_fn, trainer, cache_name: str = "module_step"):
        self.block = block
        self.loss_fn = loss_fn
        self.trainer = trainer
        self._cache: Dict[tuple, dict] = {}
        self._cache_name = cache_name
        self._last_sig: Optional[tuple] = None
        self._stats = cache_stats(cache_name)
        self._param_handles = list(trainer._params)
        self._aux_handles = [p for p in trainer._all_params
                             if p.grad_req == "null" and p._data is not None]
        # ZeRO engagement, resolved ONCE (kvstore type device/dist_sync +
        # MXTPU_ZERO + elementwise optimizer → trainer.zero_requested()):
        # the batch shards over the data axes, gradients resolve per-param
        # as named-axis reduce-scatters into packed buckets, and optimizer
        # slots live 1/N-sharded. ``MXTPU_ZERO_STAGE=3`` additionally keeps
        # every shardable param RESIDENT 1/N on the fsdp axis. Works on any
        # mesh (the old multi-axis replicated fallback is gone — per-param
        # constraint resolution is exact where the concat formulation
        # mis-reduced).
        self._zero_mesh = None
        self._zero_stage = 0
        self._param_sh = None
        self._strict_adopt = False
        if trainer.zero_requested():
            from .parallel.mesh import get_default_mesh
            from .parallel.fsdp import zero_stage
            self._zero_mesh = get_default_mesh()
            self._zero_stage = zero_stage()

    # -- ZeRO plumbing -----------------------------------------------------
    def _ensure_placed(self):
        """Place params/aux across the mesh (idempotent; the committed
        NamedSharding is part of the signature, so this runs BEFORE _sig).
        Stages 1/2 replicate everything; stage 3 keeps each shardable param
        RESIDENT 1/N on the fsdp axis (XLA all-gathers it just-in-time inside
        the compiled step and frees the gathered copy after use)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .parallel.data_parallel import _place
        mesh = self._zero_mesh
        repl = NamedSharding(mesh, P())
        if self._param_sh is None:
            if self._zero_stage >= 3:
                from .parallel import fsdp as fsdp_mod
                composed = fsdp_mod.fsdp_param_specs(
                    [tuple(p._data._data.shape) for p in self._param_handles],
                    [None] * len(self._param_handles), mesh)
                self._param_sh = [
                    NamedSharding(mesh, c) if c is not None else repl
                    for c in composed]
            else:
                self._param_sh = [repl] * len(self._param_handles)
        for p, sh in zip(self._param_handles, self._param_sh):
            raw = p._data._data
            if getattr(raw, "sharding", None) != sh:
                p._data._set_data(_place(raw, sh))
        for p in self._aux_handles:
            raw = p._data._data
            if getattr(raw, "sharding", None) != repl:
                p._data._set_data(_place(raw, repl))

    def _ensure_zero_states(self):
        """Create (or adopt from a checkpoint restore) the per-bucket sharded
        optimizer slots, owned by the Trainer so snapshot capture sees them."""
        from jax.sharding import PartitionSpec as P
        from .parallel import zero as zero_mod
        from .parallel.mesh import data_size
        tr = self.trainer
        opt = tr._optimizer
        if tr._zero_layout is not None:
            if tr._zero_layout.passthrough:
                self._ensure_pt_states()
            return
        raws = [p._data._data for p in self._param_handles]
        comp = getattr(tr._kvstore, "_compression_params", None) \
            if tr._kvstore is not None else None
        # stage 3: fsdp-resident params are NOT bucketed — they keep the
        # per-param sharded update (slots follow the param's sharding)
        layout = zero_mod.ZeroLayout(
            raws,
            [getattr(p, "lr_mult", 1.0) * opt.lr_mult.get(i, 1.0)
             for i, p in enumerate(self._param_handles)],
            [getattr(p, "wd_mult", 1.0) * opt.wd_mult.get(i, 1.0)
             for i, p in enumerate(self._param_handles)],
            data_size(self._zero_mesh),
            eligible=[sh.spec == P() for sh in self._param_sh])
        tr._zero_layout = layout
        adopted = None
        if tr._zero_restore is not None:
            saved_meta, saved_arrays = tr._zero_restore
            adopted = layout.adopt_states(saved_arrays,
                                          saved_meta.get("layout", {}),
                                          self._zero_mesh)
            tr._zero_restore = None
            if adopted is None and self._strict_adopt:
                # live resize: a silent fresh-state fallback would continue
                # training with zeroed momentum — fail so the elastic
                # controller's caller takes the process-restart path instead
                raise RuntimeError(
                    "in-place mesh adoption failed: live ZeRO optimizer "
                    "slots do not match the re-bucketed layout on the new "
                    "mesh")
            if adopted is None:
                import warnings
                warnings.warn(
                    "checkpointed ZeRO optimizer slots do not match the "
                    "current bucket layout (params or MXTPU_ZERO_BUCKET_MB "
                    "changed); starting with fresh optimizer state",
                    stacklevel=3)
        if adopted is not None:
            tr._zero_states, tr._zero_residuals = adopted
        else:
            tr._zero_states, tr._zero_residuals = zero_mod.init_zero_states(
                opt, layout, raws, self._zero_mesh,
                with_residual=comp is not None)
        # normalize residuals to the CURRENT compression setting: fresh zeros
        # where compression wants one and none was saved; dropped when off
        if comp is None:
            tr._zero_residuals = [None] * len(layout.buckets)
        else:
            from .parallel.data_parallel import _place
            shard = layout.shard_spec(self._zero_mesh)
            tr._zero_residuals = [
                r if r is not None
                else _place(jnp.zeros((b.padded,), jnp.float32), shard)
                for b, r in zip(layout.buckets, tr._zero_residuals)]
        if donation_supported():
            tr._zero_states = [unique_buffers(st) for st in tr._zero_states]
        if layout.passthrough:
            self._ensure_pt_states()

    def _ensure_pt_states(self):
        """Per-param optimizer slots for the passthrough set (fsdp-resident
        params at stage 3): each slot is placed with its PARAM's sharding, so
        state is 1/N resident without bucketing — and the checkpoint path
        (``opt:i:j`` keys + recorded specs) re-shards it across fsdp widths
        exactly like a param."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .parallel.data_parallel import _place
        tr = self.trainer
        opt = tr._optimizer
        repl = NamedSharding(self._zero_mesh, P())
        donate = donation_supported()
        for i in tr._zero_layout.passthrough:
            if tr._states[i] is not None:
                continue
            p = self._param_handles[i]
            shape = tuple(p._data._data.shape)
            st = opt.create_state_multi_precision(i, p.data())
            placed = tuple(
                _place(s, self._param_sh[i]
                       if getattr(s, "shape", None) == shape else repl)
                if hasattr(s, "dtype") else s
                for s in st)
            tr._states[i] = unique_buffers(placed) if donate else placed

    # -- live elasticity ---------------------------------------------------
    def adopt_mesh(self, mesh) -> None:
        """Re-home the fused step onto ``mesh`` IN PLACE, mid-run (live
        elasticity, ROADMAP item 4): the optimizer keeps its exact state —
        bucketed ZeRO slots are host-landed, staged through the same
        ``trainer._zero_restore`` ritual a checkpoint restore uses, and
        re-adopted via ``ZeroLayout.adopt_states`` at the NEW data size;
        per-param (stage-3 passthrough) slots re-place with their param's
        new resident sharding. The program cache is dropped (the next step
        traces once on the new mesh) and update counters / RNG are untouched,
        so the continuation is bit-exact with a cold checkpoint-resume onto
        the same mesh.

        Must be called at a step boundary (no step in flight). A bucket-
        layout mismatch on the new mesh raises — the caller (``ElasticRun``)
        falls back to a process restart rather than continuing with silently
        zeroed momentum."""
        if self._zero_mesh is None:
            raise RuntimeError(
                "adopt_mesh requires a ZeRO/FSDP-engaged step (kvstore "
                "device/dist_sync with an elementwise optimizer); the "
                "replicated eager path has no mesh to resize")
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .checkpoint.snapshot import _to_host
        from .parallel.data_parallel import _place
        tr = self.trainer
        # 1. host-land the bucketed ZeRO slots, keyed exactly like a
        #    checkpoint (zopt:{b}:{j} / zres:{b}) so the adoption below is
        #    the SAME de-interleave/re-pack path a dp-N→dp-M resume takes
        if tr._zero_layout is not None:
            zarrays, zslots = {}, []
            for b, st in enumerate(tr._zero_states):
                zslots.append(len(st))
                for j, s in enumerate(st):
                    zarrays[f"zopt:{b}:{j}"] = _to_host(s)
            for b, r in enumerate(tr._zero_residuals or []):
                if r is not None:
                    zarrays[f"zres:{b}"] = _to_host(r)
            tr._zero_restore = ({"layout": tr._zero_layout.describe(),
                                 "slots": zslots}, zarrays)
            tr._zero_layout = None
            tr._zero_states = []
            tr._zero_residuals = []
        # 2. host-land per-param slots (the stage-3 passthrough set) before
        #    their shardings go stale with the old mesh
        host_states = [
            None if st is None else
            tuple(_to_host(s) if hasattr(s, "dtype") else s for s in st)
            for st in tr._states]
        # 3. re-home: new mesh, recomputed param shardings, cold program
        #    cache (the signature includes shardings, so the first step on
        #    the new mesh must trace — dropping the cache just makes the
        #    old-mesh programs collectable)
        self._zero_mesh = mesh
        self._param_sh = None
        self._cache.clear()
        self._last_sig = None
        self._ensure_placed()
        repl = NamedSharding(mesh, P())
        donate = donation_supported()
        for i, st in enumerate(host_states):
            if st is None:
                continue
            shape = tuple(self._param_handles[i]._data._data.shape)
            placed = tuple(
                _place(s, self._param_sh[i]
                       if getattr(s, "shape", None) == shape else repl)
                if hasattr(s, "dtype") else s
                for s in st)
            tr._states[i] = unique_buffers(placed) if donate else placed
        # 4. adopt the staged slots onto the new layout — strict: a layout
        #    mismatch raises instead of silently resetting optimizer state
        self._strict_adopt = True
        try:
            self._ensure_zero_states()
        finally:
            self._strict_adopt = False

    # -- signature ---------------------------------------------------------
    def _ensure_states(self):
        tr = self.trainer
        opt = tr._optimizer
        donate = donation_supported()
        for i, p in enumerate(self._param_handles):
            if tr._states[i] is None:
                st = opt.create_state_multi_precision(i, p.data())
                tr._states[i] = unique_buffers(st) if donate else tuple(st)

    def _sig(self, data, label) -> tuple:
        tr = self.trainer
        zero_sig = None
        if self._zero_mesh is not None:
            zero_sig = (
                tr._zero_layout.fingerprint(),
                tuple(tuple(_arr_sig(s) for s in st)
                      for st in tr._zero_states),
                tuple(None if r is None else _arr_sig(r)
                      for r in tr._zero_residuals),
            )
        return (
            tuple(_arr_sig(d.data) for d in data),
            _arr_sig(label.data) if label is not None else None,
            tuple(_arr_sig(p._data._data) for p in self._param_handles),
            tuple(_arr_sig(p._data._data) for p in self._aux_handles),
            tuple(tuple(_arr_sig(s) for s in (st or ()))
                  for st in tr._states),
            tuple(p.grad_req for p in self._param_handles),
            optimizer_fingerprint(tr._optimizer),
            zero_sig,
            quant_step_mode(),   # MXTPU_QUANT_STEP: flipping modes retraces
        )

    # -- tracing -----------------------------------------------------------
    def _build(self) -> dict:
        from . import autograd, rng
        from .ndarray.ndarray import NDArray
        from .gluon.loss import SoftmaxCrossEntropyLoss

        block, loss_fn = self.block, self.loss_fn
        opt = self.trainer._optimizer
        param_handles = self._param_handles
        aux_handles = self._aux_handles
        # static per-param multipliers (the _get_lr/_get_wd composition)
        lr_mults = [getattr(p, "lr_mult", 1.0) * opt.lr_mult.get(i, 1.0)
                    for i, p in enumerate(param_handles)]
        wd_mults = [getattr(p, "wd_mult", 1.0) * opt.wd_mult.get(i, 1.0)
                    for i, p in enumerate(param_handles)]
        update_all = build_update_all(opt, lr_mults, wd_mults)
        zero_update = None
        pt: List[int] = []
        pt_update = None
        if self._zero_mesh is not None:
            from .parallel import zero as zero_mod
            comp = getattr(self.trainer._kvstore, "_compression_params", None) \
                if self.trainer._kvstore is not None else None
            zero_update = zero_mod.build_zero_update(
                opt, self.trainer._zero_layout, self._zero_mesh,
                comm_dtype=zero_mod.comm_dtype_of(comp),
                compression_params=comp)
            # fsdp-resident (stage 3) params: per-param update with the
            # gradient constrained to the param's resident sharding — the
            # pending data-axis reduction lowers to an explicit per-axis
            # reduce-scatter onto the 1/N shard
            pt = list(self.trainer._zero_layout.passthrough)
            if pt:
                pt_update = build_update_all(
                    opt, [lr_mults[i] for i in pt], [wd_mults[i] for i in pt],
                    shardings=[self._param_sh[i] for i in pt])
        softmax_expose = isinstance(loss_fn, SoftmaxCrossEntropyLoss)
        struct: dict = {}

        def pure(param_raws, aux_raws, state_raws, zstates, zres, data_raws,
                 label_raw, lr, wd, rescale, clip, t, key):
            provider = rng.push_trace_provider(key)
            saved_p = [p._data._data for p in param_handles]
            saved_a = [p._data._data for p in aux_handles]
            try:
                def loss_on(ps):
                    for p, r in zip(param_handles, ps):
                        p._data._data = r
                        p._data._version += 1
                    for p, r in zip(aux_handles, aux_raws):
                        p._data._data = r
                        p._data._version += 1
                    with autograd.pause(train_mode=True):
                        out = block(*[NDArray(d) for d in data_raws])
                        single = not isinstance(out, (tuple, list))
                        outs = [out] if single else list(out)
                        loss = loss_fn(outs[0], NDArray(label_raw))
                    struct["single"] = single
                    new_aux = [p._data._data for p in aux_handles]
                    # sum-of-loss head: eager backward seeds ones on the
                    # per-sample loss vector, which IS d(sum)/d(.)
                    return (jnp.sum(loss.data.astype(jnp.float32)),
                            (new_aux, [o.data for o in outs], loss.data))

                (_, (new_aux, raw_outs, loss_arr)), grads = \
                    jax.value_and_grad(loss_on, has_aux=True)(list(param_raws))
                if zero_update is not None:
                    # ZeRO: bucketed reduce-scatter → sharded slot update →
                    # all-gather. Grads are NOT returned in this mode: a
                    # replicated grad output would force the very all-reduce
                    # the reduce-scatter exists to avoid.
                    new_params, new_zstates, new_zres = zero_update(
                        list(param_raws), list(grads), zstates, zres,
                        lr, wd, rescale, clip, t)
                    new_states, out_grads = list(state_raws), None
                    if pt:
                        sub_w, sub_st = pt_update(
                            [new_params[i] for i in pt],
                            [grads[i] for i in pt],
                            [state_raws[i] or () for i in pt],
                            lr, wd, rescale, clip, t)
                        for j, i in enumerate(pt):
                            new_params[i] = sub_w[j]
                            new_states[i] = sub_st[j]
                else:
                    new_params, new_states = update_all(
                        param_raws, grads, state_raws, lr, wd, rescale,
                        clip, t)
                    new_zstates, new_zres, out_grads = zstates, zres, \
                        list(grads)
                exposed0 = (jax.nn.softmax(raw_outs[0], axis=-1)
                            if softmax_expose else None)
                return (new_params, new_aux, new_states, new_zstates,
                        new_zres, out_grads, loss_arr, raw_outs, exposed0)
            finally:
                for p, r in zip(param_handles, saved_p):
                    p._data._data = r
                    p._data._version += 1
                for p, r in zip(aux_handles, saved_a):
                    p._data._data = r
                    p._data._version += 1
                rng.pop_trace_provider()

        donate = (0, 2, 3, 4) if donation_supported() else ()
        jitted = jax.jit(pure, donate_argnums=donate)
        return {"jitted": jitted, "struct": struct}

    # -- FLOP accounting ---------------------------------------------------
    def program_flops(self) -> Optional[float]:
        """FLOPs of ONE execution of the current compiled step program (XLA
        cost analysis; analytic conv/matmul jaxpr count as fallback —
        ``observability.flops.estimate_step_flops``). Lazy and cached per
        cache entry: the first call after a trace pays one AOT lower+compile,
        subsequent calls are a dict read — callers (fit epoch logs, bench)
        keep this OFF the step hot path."""
        entry = self._cache.get(self._last_sig)
        if entry is None or "avals" not in entry:
            return None
        if "flops" not in entry:
            from .observability import flops as flops_mod
            entry["flops"] = flops_mod.estimate_step_flops(entry["jitted"],
                                                           entry["avals"])
            flops_mod.set_step_flops(entry["flops"])
        return entry["flops"]

    def audit_entry(self):
        """``(jitted program, abstract args)`` of the most recently
        dispatched fused-step signature — the program auditor's entry point
        (``python -m mxtpu.analysis --audit``).  The avals are the same
        shape/dtype skeleton :meth:`program_flops` lowers against, so the
        auditor re-traces the EXACT program the trainer runs (donation map
        included) without pinning any live buffers.  Raises until one real
        step has populated the cache."""
        entry = self._cache.get(self._last_sig)
        if entry is None or "avals" not in entry:
            raise RuntimeError(
                "audit_entry: no fused step has been dispatched yet — run "
                "one training step before auditing the step program")
        return entry["jitted"], entry["avals"]

    # -- the step ----------------------------------------------------------
    def step(self, data: Sequence, label, batch_size: Optional[int] = None):
        """Run one fused train step. Returns a dict with detached
        ``loss`` (per-sample array), ``outputs``, and ``exposed`` (softmaxed
        outputs when the loss is classification, else None)."""
        from . import rng
        from .analysis import sanitize
        from .ndarray.ndarray import NDArray
        from .observability import tracer
        from .resilience import fault_point
        from .resilience.watchdog import heartbeat

        # resilience seam FIRST — before the RNG advances below — so a fault
        # (or preemption save) fired here leaves per-step RNG state identical
        # to a run that never reached this step; heartbeat feeds the
        # per-step deadline watchdog and the supervisor's progress beacon
        fault_point("step")
        heartbeat("step")

        san = sanitize.active()
        tr = self.trainer
        tr._init_kvstore()
        opt = tr._optimizer
        if self._zero_mesh is not None:
            # ZeRO-1: replicate params over the dp mesh, dp-shard the batch,
            # keep optimizer slots ONLY as 1/N bucket shards (tr._states
            # stays None — snapshot capture reads tr._zero_states instead)
            from .parallel.data_parallel import shard_batch
            self._ensure_placed()
            self._ensure_zero_states()
            data = [shard_batch(d, self._zero_mesh) for d in data]
            if label is not None:
                label = shard_batch(label, self._zero_mesh)
        else:
            self._ensure_states()
        batch_size = batch_size if batch_size is not None else data[0].shape[0]

        sig = self._sig(data, label)
        entry = self._cache.get(sig)
        traced_now = entry is None
        if traced_now:
            if "retrace" in san and self._cache:
                # raises RetraceError with a labeled signature diff BEFORE
                # paying for the compile; the limit defaults to 2 (train +
                # eval — the compile-guard contract)
                sanitize.escalate_retrace(self._cache_name, len(self._cache),
                                          self._last_sig, sig,
                                          labels=_SIG_LABELS)
            self._stats.miss()
            entry = self._cache[sig] = self._build()
        else:
            self._stats.hit()
        self._last_sig = sig

        t = max([opt._index_update_count.get(i, 0)
                 for i in range(len(self._param_handles))] or [0]) + 1
        # eager parity: _update_count precedes _get_lr, so the scheduler sees
        # the post-increment num_update
        lr = jnp.float32(opt.lr_scheduler(max(opt.num_update, t))
                         if opt.lr_scheduler else opt.lr)
        wd = jnp.float32(opt.wd)
        rescale = jnp.float32(tr._scale / batch_size)
        clip = jnp.float32(opt.clip_gradient
                           if opt.clip_gradient is not None else 0.0)
        key = rng.next_key()

        # donated argument groups, held as locals so the donation sanitizer
        # can poison exactly what the compiled program consumed. ``t`` goes
        # in as int32 so the transfer guard sees no per-step host scalar.
        param_raws = [p._data._data for p in self._param_handles]
        aux_raws = [p._data._data for p in self._aux_handles]
        state_raws = list(tr._states)
        zstate_raws = list(tr._zero_states)
        zres_raws = list(tr._zero_residuals)
        data_raws = [d.data for d in data]
        label_raw = label.data if label is not None else None
        t_arr = jnp.int32(t)
        step_args = (param_raws, aux_raws, state_raws, zstate_raws, zres_raws,
                     data_raws, label_raw, lr, wd, rescale, clip, t_arr, key)
        if traced_now:
            # shape/dtype skeleton for the lazy FLOP estimate (program_flops)
            # — holding real arrays would pin donated buffers
            entry["avals"] = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
                if hasattr(a, "shape") else a, step_args)
            if self._zero_mesh is not None:
                # per-device residency accounting, from the placed shardings
                from .parallel import fsdp as fsdp_mod
                slots = [s for st in list(tr._states) + list(tr._zero_states)
                         for s in (st or ()) if hasattr(s, "dtype")]
                slots += [r for r in tr._zero_residuals if r is not None]
                grad_bytes = sum(fsdp_mod.replicated_bytes(a)
                                 for a in param_raws)
                fsdp_mod.measure_memory(self._zero_stage, self._zero_mesh,
                                        param_raws, slots, grad_bytes)
        # one span per dispatch on the unified step timeline: the first call
        # of a signature IS the trace+lower+compile (step/compile, tagged
        # with the signature fingerprint), cache hits are step/execute
        sp = tracer.span("step/compile" if traced_now else "step/execute",
                         cat="step",
                         args={"cache": self._cache_name,
                               "signature":
                               f"{hash(sig) & 0xffffffffffffffff:016x}"}
                         if traced_now else {"cache": self._cache_name})
        with sp, sanitize.step_guard(san, traced_now, where=self._cache_name), \
                quant_scope(sig[-1]):
            # quant_scope swaps the dense/conv contraction for the fake-quant
            # STE path while THIS signature's program traces (no-op when the
            # mode is off or the program is already compiled)
            out = entry["jitted"](*step_args)
        (new_params, new_aux, new_states, new_zstates, new_zres, grads,
         loss_arr, raw_outs, exposed0) = out

        if "donation" in san:
            # the program consumed argnums (0, 2, 3, 4): params, optimizer
            # slots, ZeRO slots/residuals. Poison the old references (minus
            # pass-throughs the program returned unchanged) so a stale read
            # raises a NAMED error here on CPU too — where XLA skips
            # donation and the PR 2 snapshot race was silent.
            donated = list(param_raws)
            for st in state_raws:
                donated.extend(st or ())
            for st in zstate_raws:
                donated.extend(st or ())
            donated.extend(r for r in zres_raws if r is not None)
            returned = {id(v) for v in new_params}
            for group in (new_states, new_zstates):
                for st in group:
                    returned.update(id(s) for s in (st or ()))
            returned.update(id(r) for r in new_zres if r is not None)
            sanitize.poison(
                (a for a in donated if id(a) not in returned),
                origin=f"the fused '{self._cache_name}' step "
                       f"(donate_argnums params/opt-state)")

        # write-back: params/aux/state swap + eager-visible gradients
        for p, v in zip(self._param_handles, new_params):
            p._data._set_data(v)
        for p, v in zip(self._aux_handles, new_aux):
            p._data._set_data(v)
        tr._states = list(new_states)
        tr._zero_states = list(new_zstates)
        tr._zero_residuals = list(new_zres)
        if grads is not None:
            # eager-visible gradients (param.grad()); the ZeRO path skips
            # this — materializing the full grad would force an all-reduce
            for p, g in zip(self._param_handles, grads):
                h = p._data
                if h._grad is not None and getattr(h._grad, "stype",
                                                   "default") == "default":
                    h._grad._set_data(g)
                else:
                    h._grad = NDArray(g)
        for i in range(len(self._param_handles)):
            opt._index_update_count[i] = t
        opt.num_update = max(opt.num_update, t)
        if self._zero_mesh is not None:
            from . import profiler
            profiler.record_comm_step(zero=True,
                                      **tr._zero_layout.step_comm())

        outputs = [NDArray(r) for r in raw_outs]
        return {
            "loss": NDArray(loss_arr),
            "outputs": outputs[0] if entry["struct"].get("single", True)
            and len(outputs) == 1 else outputs,
            "outputs_list": outputs,
            "exposed": ([NDArray(exposed0)] + outputs[1:]
                        if exposed0 is not None else None),
        }
