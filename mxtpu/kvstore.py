"""KVStore — parity with ``src/kvstore/`` + ``python/mxnet/kvstore.py`` (SURVEY.md §2.3).

The reference's KVStore hierarchy (local CPU-reduce / device P2P-reduce / NCCL /
ps-lite dist_sync|dist_async) exists because GPUs need explicit reduction and clusters
need a parameter server. On TPU the same *semantics* (named values, push accumulates a
reduction, pull reads, optional server-side updater, rank/size/barrier) sit on two
mechanisms:

* intra-process: handles are single logical arrays; "reduce over devices" degenerates
  to summing the pushed list (multi-device data-parallelism is expressed with sharded
  arrays, where XLA inserts the all-reduce — see ``mxtpu.parallel``).
* inter-process (``dist_sync``): ``jax.distributed`` supplies rank/size, and pushed
  grads are all-reduced over the pod with an XLA collective (``parallel.collectives``) —
  replacing ps-lite push/pull (kvstore_dist.h) with ICI/DCN allreduce, per BASELINE's
  north star. Sync semantics match ``dist_sync`` (every worker sees the same reduced
  value). ``dist_async`` keeps the reference's asynchronous-SGD semantics via a
  HOST-side parameter server (``mxtpu.ps``): rank 0 owns the authoritative copy,
  pushes apply the server-side optimizer the moment they arrive, pulls read the
  current state — no worker synchronization (kvstore_dist_server.h async mode).

Types accepted for parity: local | device | tpu | dist | dist_sync | dist_device_sync
(kvstore.cc:40-76 type strings; nccl → tpu).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp

from .ndarray.ndarray import NDArray
from . import optimizer as opt_mod

__all__ = ["KVStore", "create"]


def create(name: str = "local") -> "KVStore":
    return KVStore(name)


class KVStore:
    def __init__(self, kv_type: str = "local"):
        kv_type = {"nccl": "tpu", "device": "tpu"}.get(kv_type, kv_type)
        if kv_type.startswith("dist"):
            self._distributed = True
            if "async" not in kv_type:
                # connect the pod if the launcher's DMLC_* env contract is
                # present (tools/launch.py; InitPSEnv parity kvstore.h:257).
                # The async mode deliberately skips this: its transport is the
                # host-side PS, and blocking on the jax.distributed
                # coordinator would reintroduce worker synchronization.
                from . import dist as dist_mod
                dist_mod.auto_initialize()
        elif kv_type in ("local", "local_allreduce_cpu", "local_allreduce_device",
                         "tpu"):
            self._distributed = False
        else:
            raise ValueError(f"unknown kvstore type {kv_type!r}")
        self._async = "async" in kv_type
        self._ps = None
        if self._async:
            # dist_async: XLA collectives are synchronous, so async SGD runs
            # where the reference ran it — a HOST-side parameter server
            # (mxtpu/ps.py; kvstore_dist_server.h async-mode parity: pushes
            # apply on arrival, no aggregation wait)
            import os

            from . import ps as ps_mod
            self._ps_world = int(os.environ.get("DMLC_NUM_WORKER", "1"))
            self._ps_rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
            host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
            port = ps_mod.default_port()
            if port == 0 and self._ps_world > 1:
                # non-zero ranks derive the port from env alone — an ephemeral
                # binding on rank 0 could never be discovered by them
                raise ValueError(
                    "MXTPU_PS_PORT=0 (ephemeral) is only valid single-worker: "
                    "with DMLC_NUM_WORKER>1 every rank must share a concrete "
                    "port; set MXTPU_PS_PORT or DMLC_PS_ROOT_PORT")
            if self._ps_rank == 0:
                # port 0 (ephemeral) works single-worker: the bound port is
                # read back from the socket
                port = ps_mod.start_server(port, self._ps_world).port
            self._ps = ps_mod.PSClient(host, port)
        self.type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._optimizer: Optional[opt_mod.Optimizer] = None
        self._compression_params: Optional[dict] = None

    # -- identity ----------------------------------------------------------
    @property
    def rank(self) -> int:
        if self._async:
            return self._ps_rank
        return jax.process_index() if self._distributed else 0

    @property
    def num_workers(self) -> int:
        if self._async:
            return self._ps_world
        return jax.process_count() if self._distributed else 1

    def barrier(self):
        if self._async:
            self._ps.barrier()        # server-side count-to-world barrier
        elif self._distributed and jax.process_count() > 1:
            # a tiny psum over all processes is the canonical XLA barrier
            from .parallel import collectives
            collectives.process_barrier()

    # -- data --------------------------------------------------------------
    def init(self, key, value):
        import numpy as np
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                # materialized copy, not an alias: the caller's weight buffer may be
                # donated by a later optimizer step (see NDArray.copy)
                self._store[k] = NDArray(jnp.array(v.data, copy=True))
            if self._async:
                self._ps.init(str(k), np.asarray(v.data))  # server first-wins

    def push(self, key, value, priority: int = 0):
        """Accumulate: list-of-values are reduced (Comm::Reduce parity, comm.h:103);
        in dist mode the reduced grad is all-reduced across workers.

        SPMD contract (dist_sync): every rank must push the SAME storage type
        for a given key — grad stype is a property of the parameter, as in the
        reference (kvstore_dist.h dispatches DataHandleRowSparse vs Default by
        the key's stype). The sparse path issues a different collective
        sequence (row-union exchange) than the dense path; ranks disagreeing
        on a key's stype would hang the job, exactly like mismatched NCCL
        calls. A rank with no live rows pushes an EMPTY row_sparse grad, not
        a dense zero."""
        from .ndarray import sparse as _sparse
        keys, values = self._normalize_push(key, value)
        if self._async:
            # async PS: locally reduce the pushed list, ship the grad; the
            # SERVER applies its updater immediately on arrival (no
            # worker-sync). Row-sparse grads ship ONLY their live rows
            # (CMD_PUSH_ROWS — kvstore_dist_server.h row_sparse async parity).
            import numpy as np
            for k, vlist in zip(keys, values):
                if all(getattr(v, "stype", "default") == "row_sparse"
                       for v in vlist):
                    red = vlist[0]
                    for v in vlist[1:]:
                        red = _sparse.add(red, v)
                    self._ps.push_rows(str(k), np.asarray(red._indices),
                                       np.asarray(red._values))
                    continue
                red = None
                for v in vlist:
                    dense = v._dense() if getattr(
                        v, "stype", "default") == "row_sparse" else v.data
                    red = dense if red is None else red + dense
                self._ps.push(str(k), np.asarray(red))
            return
        for k, vlist in zip(keys, values):
            if any(getattr(v, "stype", "default") == "row_sparse" for v in vlist):
                # sparse push (kvstore_dist.h:436 DataHandleRowSparse semantics):
                # reduce the pushed row-sparse grads, keep them sparse through the
                # updater so lazy optimizers touch only the live rows
                red = vlist[0]
                for v in vlist[1:]:
                    red = _sparse.add(red, v)
                if self._distributed and jax.process_count() > 1:
                    red = self._transport_rowsparse(red)
                if self._updater is not None:
                    self._updater(k, red, self._store[k])
                else:
                    # KVStoreLocal::PushImpl assigns local = merged: unpushed
                    # rows become zero, not stale (kvstore_local.h:162-189)
                    self._store[k] = NDArray(
                        red._dense().astype(self._store[k].dtype))
                continue
            red = vlist[0].data
            for v in vlist[1:]:
                red = red + v.data
            if self._compression_params is not None:
                # worker-side compression BEFORE transport (the reference
                # compresses before the dist push for wire-bandwidth,
                # gradient_compression.h:37-134 + kvstore_dist.h): the int8
                # sign codes are what crosses the wire; the residual stays
                # per-rank; decode happens after the sum
                codes = self._transport(self._compress_encode(k, red))
                red = self._decode(codes).astype(red.dtype)
            else:
                red = self._transport(red)
            if self._updater is not None:
                grad = NDArray(red)
                self._updater(k, grad, self._store[k])
            else:
                self._store[k] = NDArray(red)

    def pull(self, key, out=None, priority: int = 0, ignore_sparse: bool = True):
        keys, outs = self._normalize_push(key, out)
        for k, olist in zip(keys, outs):
            if self._async:
                fetched = jnp.asarray(self._ps.pull(str(k)))
                self._store[k] = NDArray(fetched)   # cache the latest view
                src = self._store[k]
            else:
                src = self._store[k]
            for o in olist:
                o._set_data(src.data.astype(o.dtype).reshape(o.shape))

    def pushpull(self, key, value, out=None, priority: int = 0):
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority: int = 0, row_ids=None):
        """Sparse pull (kvstore_dist.h:436-510): fetch ONLY the requested rows.

        If ``out`` is a RowSparseNDArray it receives exactly the deduped requested
        rows (true sparse pull — O(|rows|) transfer, the capability the reference row
        exists for); a dense ``out`` gets the rows scattered in place.
        """
        import numpy as np
        from .ndarray import sparse as _sparse
        if row_ids is None:
            return self.pull(key, out, priority)
        keys, outs = self._normalize_push(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids] * len(outs[0])
        for k, olist in zip(keys, outs):
            src = None if self._async else self._store[k]
            for i, (o, rid) in enumerate(zip(olist, rids)):
                rid_host = np.unique(np.asarray(
                    rid.asnumpy() if hasattr(rid, "asnumpy") else rid).astype(
                        np.int64).reshape(-1))
                rows = jnp.asarray(rid_host, jnp.int32)
                if self._async:
                    # O(|rows|) wire: the server ships only the requested rows
                    # (CMD_PULL_ROWS; kvstore_dist.h:436-510 sparse pull parity)
                    gathered = jnp.asarray(
                        self._ps.pull_rows(str(k), rid_host))
                else:
                    gathered = src.data[rows]
                if getattr(o, "stype", "default") == "row_sparse":
                    o._indices = rows
                    o._values = gathered.astype(o.dtype)
                else:
                    o._set_data(o.data.at[rows].set(gathered.astype(o.dtype)))

    # -- updater / optimizer ----------------------------------------------
    def set_optimizer(self, optimizer):
        self._optimizer = opt_mod.create(optimizer) if not isinstance(
            optimizer, opt_mod.Optimizer) else optimizer
        if self._async:
            # ship the (picklable) optimizer to the server — reference
            # kvstore.py set_optimizer serializes it for the server role
            self._ps.set_optimizer(self._optimizer)
            return
        self._updater = opt_mod.get_updater(self._optimizer)

    def _set_updater(self, updater: Callable):
        if self._async:
            raise NotImplementedError(
                "dist_async applies updates on the server: use "
                "set_optimizer(...) (serialized to the server role) instead "
                "of an arbitrary local updater callable")
        self._updater = updater

    def set_gradient_compression(self, compression_params: dict):
        """Gradient compression with error-feedback residual before reduction
        (gradient_compression.h:37). Kinds: ``2bit`` quantizes to
        {-threshold, 0, +threshold} (reference parity); ``fp16``/``bf16``
        lower the comm-payload dtype (the wire/collective carries half-width
        grads; the cast error re-enters the next push via the residual).
        Unknown kinds are rejected up front — a silent ignore here would
        train uncompressed while the user budgets wire bandwidth for
        compressed. The same dict drives the ZeRO-1 fused step's bucket
        payload (``parallel/zero.py``) when this store backs a Trainer."""
        from .parallel import zero as zero_mod
        zero_mod.comm_dtype_of(compression_params)   # validates the kind
        self._compression_params = dict(compression_params)
        self._residuals: Dict[Any, jnp.ndarray] = {}

    def _transport(self, payload):
        """The cross-worker hop: everything that 'crosses the wire' funnels
        through here (tests hook it to inspect the payload)."""
        if self._distributed and jax.process_count() > 1:
            from .parallel import collectives
            return collectives.allreduce_processes(payload)
        return payload

    def _transport_rowsparse(self, red):
        """Cross-worker row-sparse reduce with O(rows) payload: allgather row
        ids, sum values over the union slab — never the dense matrix
        (kvstore_dist.h:436-510 DataHandleRowSparse parity; tests hook this
        and the collectives beneath it to audit wire bytes)."""
        from .ndarray import sparse as _sparse
        from .parallel import collectives
        rows, vals = collectives.allreduce_rowsparse_processes(
            red._indices, red._values, red.shape[0])
        return _sparse.RowSparseNDArray(rows, vals, red.shape)

    def _compress_encode(self, key, grad):
        """Worker-side encode with error-feedback residual
        (gradient_compression.h:37-134). ``2bit``: int8 codes in {-1, 0, +1},
        decoded as ``codes * threshold`` (int8, not 2-bit packed, is the
        practical XLA-collective payload — still 4x vs f32). ``fp16``/
        ``bf16``: the codes ARE the half-width gradient (2x wire saving);
        either way the quantization error stays per-rank and re-enters the
        next push."""
        kind = self._compression_params.get("type", "2bit")
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros_like(grad)
        g = grad + res
        if kind == "2bit":
            thr = float(self._compression_params.get("threshold", 0.5))
            codes = (jnp.where(g >= thr, 1, 0) +
                     jnp.where(g <= -thr, -1, 0)).astype(jnp.int8)
        else:
            codes = g.astype(jnp.float16 if kind == "fp16" else jnp.bfloat16)
        self._residuals[key] = g - self._decode(codes).astype(g.dtype)
        return codes

    def _decode(self, codes):
        """Inverse of _compress_encode (threshold lives in one place)."""
        if self._compression_params.get("type", "2bit") == "2bit":
            thr = float(self._compression_params.get("threshold", 0.5))
            return codes.astype(jnp.float32) * thr
        return codes.astype(jnp.float32)

    def save_optimizer_states(self, fname: str, dump_optimizer: bool = False):
        if self._async:
            # async mode: the authoritative optimizer state lives on the server
            with open(fname, "wb") as f:
                f.write(self._ps.get_optimizer_states())
            return
        if self._updater is None:
            raise RuntimeError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname: str):
        if self._async:
            with open(fname, "rb") as f:
                self._ps.set_optimizer_states(f.read())
            return
        if self._updater is None:
            raise RuntimeError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    # -- helpers -----------------------------------------------------------
    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            return list(key), list(value)
        return [key], [value]

    def _normalize_push(self, key, value):
        if isinstance(key, (list, tuple)):
            return list(key), [v if isinstance(v, (list, tuple)) else [v]
                               for v in value]
        return [key], [value if isinstance(value, (list, tuple)) else [value]]
