"""Batched prefill admission: several pending prompts, one chunk program.

The plain engine admits one prompt at a time — each scheduler turn
advances ONE partial prefill by one fixed-budget chunk (``kv.build_
prefill_chunk``, B=1). Under a burst that serializes time-to-first-token
across the whole arrival wave. This module packs up to ``N`` pending
prompts into the batch dimension of one compiled chunk program instead:

* the program is keyed ``(N, PB, csize)`` — N is the engine's configured
  ``prefill_batch`` (short groups are padded with inert rows) and PB the
  group's max prompt bucket, so any mix of prompts retraces nothing;
* every per-request quantity — prompt row, prompt length ``t0``, cursor,
  previous token, and the sampling triple — rides as a traced ``(N,)``
  vector, exactly like the decode program's slot state;
* ONE position cursor is shared by all rows, starting at the SHALLOWEST
  member's prefix-cache match. A member whose own match is deeper simply
  recomputes its cached span: those positions are all forced prompt
  positions (``t < t0``), so the recomputed K/V rows are bit-identical to
  the installed cached rows and nothing is emitted for them — and since
  the group must scan from the shallowest start anyway, the deep rows
  ride along at zero wall-clock cost. Rows that run past their own work
  (padding, overshoot) re-feed the token they last fed at the clamped
  position ``PB - 1``, an identical-rewrite no-op for the same reason:
  K/V at position ``p`` is a pure function of tokens ``0..p``.

The cross-chunk carry is (page, prev, lastfed) — running the chunks back
to back reproduces each member's monolithic prefill scan token for token,
which is the same bit-exactness-by-construction argument
``kv.build_prefill_chunk`` makes for B=1. :class:`PrefillGroup` owns the
host-side cursors; the engine dispatches one chunk per scheduler turn
(the decode-stall bound is unchanged — one chunk of work, now shared by
up to N admissions).
"""

from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..serving import kv
from ..serving.kv import _step_fn

__all__ = ["build_prefill_batch", "PrefillGroup"]


def build_prefill_batch(model, N: int, PB: int, csize: int, quant=None,
                        decode_kernel=None):
    """One compiled batched prefill CHUNK program for (rows ``N``, prompt
    bucket ``PB``, chunk size ``csize``). Returns ``run(params, page,
    prompts (N, PB) i32, t0 (N,), start (N,), prev (N,), lastfed (N,),
    temp (N,) f32, topk (N,) i32, seed (N,) u32) -> (page
    (L,2,N,H,PB,D), prev, lastfed, outs (csize, N))`` where ``outs[j, n]``
    is row ``n``'s token for position ``start[n] + j + 1``; the valid
    generated tokens of a chunk are those with ``t0 - 1 <= start + j <
    PB`` (per row, decided on the host from scalar cursors). ``quant`` /
    ``decode_kernel`` select the quantized step and fused KV read exactly
    as in :func:`~mxtpu.serving.kv.build_prefill_chunk`."""
    step = _step_fn(model, N, PB, quant, decode_kernel)
    sample = model.serving_sample()

    def run(params, page, prompts, t0, start, prev, lastfed,
            temp, topk, seed):
        def body(carry, j):
            page, prev, lastfed = carry
            t = start + j
            live = t < PB
            pos = jnp.minimum(t, PB - 1)
            ptok = jnp.take_along_axis(prompts, pos[:, None], axis=1)[:, 0]
            fed = jnp.where(live, jnp.where(t < t0, ptok, prev), lastfed)
            new_page, logits = step(params, page, fed, pos)
            nxt = sample(logits, temp, topk, seed, pos)
            return (new_page,
                    jnp.where(live, nxt, prev),
                    jnp.where(live, fed, lastfed)), nxt

        (page, prev, lastfed), outs = lax.scan(
            body, (page, prev, lastfed), jnp.arange(csize, dtype=jnp.int32))
        return page, prev, lastfed, outs

    return jax.jit(run)


class PrefillGroup:
    """Host-side cursor state for one in-flight batched prefill.

    ``members`` is a list of per-request dicts (engine-owned shape:
    ``req`` / ``slot`` / ``t0`` / ``start`` (prefix-match length) /
    ``left`` / ``done`` / ``blocks`` (cached K/V rows, consumed here) /
    sampling triple); row ``n`` of the traced vectors belongs to
    ``members[n]``, rows past ``len(members)`` are padding — their
    ``t0 = PB`` keeps them feeding forced token 0 into their own
    discarded page row for the whole scan. All rows share ONE cursor
    advanced by ``csize`` per dispatched chunk, starting at the
    shallowest member's prefix match (see module docstring for why deeper
    matches riding along is both correct and free)."""

    def __init__(self, model, members: List[dict], N: int, PB: int,
                 kv_dtype, quant):
        if not members or len(members) > N:
            raise ValueError(f"bad group size {len(members)} for batch {N}")
        self.members = members
        self.N, self.PB = N, PB
        prompts = np.zeros((N, PB), np.int32)
        t0 = np.full(N, PB, np.int32)
        temp = np.zeros(N, np.float32)
        topk = np.zeros(N, np.int32)
        seed = np.zeros(N, np.uint32)
        page = kv.empty_cache(model, N, PB, kv_dtype, quant)
        for n, mem in enumerate(members):
            req = mem["req"]
            prompts[n, :len(req.prompt)] = req.prompt
            t0[n] = mem["t0"]
            temp[n], topk[n], seed[n] = (mem["temp"], mem["topk"],
                                         mem["seed"])
            blocks = mem.pop("blocks", None)
            if mem["start"] and blocks:
                row = kv.install_rows(
                    kv.empty_page(model, PB, kv_dtype, quant),
                    blocks, mem["start"])
                page = kv.merge_page(page, row, n)
        self.prompts = jnp.asarray(prompts)
        self.t0_np = t0
        self.t0 = jnp.asarray(t0)
        self.temp, self.topk = jnp.asarray(temp), jnp.asarray(topk)
        self.seed = jnp.asarray(seed)
        self.prev = jnp.zeros(N, jnp.int32)
        self.lastfed = jnp.zeros(N, jnp.int32)
        self.page = page
        # shallowest member's match, aligned DOWN to the 32-token block
        # grid: a partial-block tail is re-fed as an identical rewrite,
        # and the aligned cursor keeps the ("batch", N, PB, csize) program
        # keys to at most PB/32 shapes (each distinct csize is a separate
        # multi-second XLA compile)
        lo = min(mem["start"] for mem in members)
        self.cursor = lo - (lo % kv.PrefixCache.BLOCK)

    def remaining(self) -> int:
        """Positions still to scan before every member row is done."""
        return max(self.PB - self.cursor, 0)

    def chunk_inputs(self):
        """Traced inputs for one dispatch of :func:`build_prefill_batch`
        at the current cursor."""
        start = jnp.full((self.N,), self.cursor, jnp.int32)
        return (self.page, self.prompts, self.t0, start, self.prev,
                self.lastfed, self.temp, self.topk, self.seed)

    def valid_range(self, n: int, csize: int):
        """Host-side emission rule for member ``n`` over the chunk just
        dispatched: ``(j_lo, j_hi)`` indices into ``outs[:, n]`` (empty
        when ``j_lo >= j_hi``). Valid tokens satisfy
        ``t0 - 1 <= cursor + j < PB``."""
        j_lo = max(int(self.t0_np[n]) - 1 - self.cursor, 0)
        j_hi = min(csize, self.PB - self.cursor)
        return j_lo, j_hi

    def advance(self, page, prev, lastfed, csize: int) -> None:
        self.page, self.prev, self.lastfed = page, prev, lastfed
        self.cursor += csize

    def member_page(self, n: int):
        """Row ``n``'s finished ``(L, 2, 1, H, PB, D)`` page, ready for
        ``kv.merge_page`` into a decode slot (or prefix-cache insert)."""
        return kv.slot_page(self.page, n)
