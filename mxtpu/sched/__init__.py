"""mxtpu.sched — multi-tenant SLO-aware serving control plane.

Sits between the ``ServingEngine`` admission queue and its scheduler
thread, strictly OPT-IN (``ServingEngine(sched=...)``; without it the
engine is byte-identical to the plain FIFO path):

* :mod:`.policy` — priority tiers + weighted fair share across tenants,
  latency-tier preemption of decode slots (park the paged-KV block,
  re-enter the queue, bit-exact on resume), and deadline shedding with a
  distinct :exc:`~mxtpu.serving.api.ShedError` so callers can tell
  "rejected early under overload" from "queue full".
* :mod:`.admission` — batched prefill: the suffixes of several pending
  prompts packed into ONE fixed-budget chunk program's batch dimension,
  keyed so programs never retrace per prompt mix.
* :mod:`.autoscale` — a controller reading the PR 15 exporter histograms
  (TTFT p99, queue-wait p99, slot occupancy) against per-tier SLO
  targets and driving ``ElasticRun.request_resize`` / a drain→adopt
  respawn callable, with hysteresis, cooldown, and a dry-run mode.
* :mod:`.replay` — deterministic bursty / diurnal / heavy-tail arrival
  traces over shared-prefix multi-tenant populations, the workload
  behind ``bench.py traffic`` and its ``goodput_under_slo`` ratchet.

See ``docs/serving.md`` (scheduling section) and
``docs/observability.md`` (autoscaler signal table).
"""

from .admission import PrefillGroup, build_prefill_batch
from .autoscale import AutoscalePolicy, Autoscaler
from .policy import DEFAULT_TIERS, SLOPolicy, SLOScheduler, TierSpec
from .replay import (KINDS, TenantProfile, TrafficRequest, TrafficTrace,
                     make_trace)

__all__ = ["SLOPolicy", "SLOScheduler", "TierSpec", "DEFAULT_TIERS",
           "PrefillGroup", "build_prefill_batch",
           "Autoscaler", "AutoscalePolicy",
           "TrafficRequest", "TenantProfile", "TrafficTrace", "make_trace",
           "KINDS"]
