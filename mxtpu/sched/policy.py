"""SLO classes and the multi-tenant scheduling policy.

This module is the *decision* half of the serving control plane: given the
engine's pending queue and slot occupancy it answers "what runs next?" —
the engine (``mxtpu.serving.engine``) stays the *execution* half and asks
at each scheduler-loop turn. Three decisions live here:

* **admission order** — strict latency-tier priority (``interactive`` >
  ``standard`` > ``batch``) and, within a tier, weighted fair-share across
  tenants via stride scheduling: each tenant carries a *pass* value
  advanced by ``prompt_tokens / weight`` when one of its requests is
  picked and by ``delivered_tokens / weight`` as decode actually serves
  it (:meth:`SLOScheduler.charge_tokens` — so a speculative verify turn
  that lands several tokens bills all of them, not one turn), and the
  pending request of the lowest-pass tenant goes next, so
  a tenant flooding the queue cannot starve the others no matter how many
  requests it stacks up (selection and charging are split — see
  :meth:`SLOScheduler.charge` — so a saturated engine re-selecting every
  turn does not inflate anyone's pass);
* **deadline shedding** — a pending request whose deadline is predicted
  unmeetable from the measured prefill/decode rates is rejected
  immediately with :exc:`~mxtpu.serving.api.ShedError` instead of burning
  prefill budget on work that would expire anyway (estimates are EWMAs fed
  by the engine's own step observations; a cold scheduler never sheds);
* **preemption victims** — when a tier with ``preempts=True`` is pending
  and no decode slot is free, :meth:`SLOScheduler.pick_victim` names the
  lowest-priority preemptible running request; the engine parks its paged
  KV block and re-enters it into the queue (bit-exact on resume — see
  ``docs/serving.md``).

The scheduler holds NO references to engine internals and touches no jax
state, so every decision is unit-testable with plain fake requests. The
only per-request state is the ``_inflight`` map, evicted in
:meth:`forget` when the engine retires the request (tpulint R008 flags
the grow-without-evict shape).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..serving.api import ShedError, TIERS

__all__ = ["TierSpec", "SLOPolicy", "SLOScheduler", "DEFAULT_TIERS"]


@dataclass(frozen=True)
class TierSpec:
    """One latency tier: admission rank, its TTFT service objective, and
    whether it may evict (or be evicted from) a decode slot. ``rank`` 0 is
    the most latency-sensitive; lower rank always admits first.
    ``ttft_slo_ms`` is the target the autoscaler and the traffic-replay
    goodput accounting measure against — not a hard per-request limit
    (that is the request's own ``deadline_s``)."""
    name: str
    rank: int
    ttft_slo_ms: float
    preempts: bool = False
    preemptible: bool = True


DEFAULT_TIERS: Dict[str, TierSpec] = {
    "interactive": TierSpec("interactive", 0, ttft_slo_ms=250.0,
                            preempts=True, preemptible=False),
    "standard": TierSpec("standard", 1, ttft_slo_ms=1000.0),
    "batch": TierSpec("batch", 2, ttft_slo_ms=10_000.0),
}


@dataclass(frozen=True)
class SLOPolicy:
    """Declarative knobs for :class:`SLOScheduler`.

    ``tenant_weights`` maps tenant name -> fair-share weight (unlisted
    tenants get ``default_weight``); a weight-2 tenant is served twice the
    tokens of a weight-1 tenant under contention. ``shed_margin``
    multiplies the service-time estimate before comparing against the
    deadline — > 1 sheds conservatively early, < 1 gambles. ``preemption``
    gates tier preemption globally (fair-share and shedding still apply
    when off)."""
    tiers: Dict[str, TierSpec] = field(
        default_factory=lambda: dict(DEFAULT_TIERS))
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    shed_margin: float = 1.2
    preemption: bool = True

    def __post_init__(self):
        for name in TIERS:
            if name not in self.tiers:
                raise ValueError(f"policy is missing tier {name!r}")
        for t, w in self.tenant_weights.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0, got {w}")


class SLOScheduler:
    """Stateful scheduler instance — one per engine, driven from the
    engine's scheduler thread (submit threads only :meth:`register`).
    All mutation is behind one lock; no method blocks or calls back into
    the engine."""

    # EWMA smoothing for the service-rate estimates; ~10 observations to
    # converge, fast enough to track a load shift within one burst
    ALPHA = 0.3

    def __init__(self, policy: Optional[SLOPolicy] = None):
        self.policy = policy if policy is not None else SLOPolicy()
        self._lock = threading.Lock()
        # tenant -> stride pass (fair-share position, in weighted tokens);
        # bounded by tenant count, never by request count
        self._pass: Dict[str, float] = {}
        # req.id -> tenant, evicted in forget() when the engine retires the
        # request — the R008 leak shape if the pop were missing
        self._inflight: Dict[int, str] = {}
        self._ewma_decode_s: Optional[float] = None   # s per generated token
        self._ewma_prefill_s: Optional[float] = None  # s per prefilled token
        self.picks = 0
        self.sheds = 0
        self.preemptions = 0
        self.resumes = 0

    # -- tier / weight lookups ---------------------------------------------
    def tier(self, req) -> TierSpec:
        return self.policy.tiers.get(getattr(req, "priority", "standard"),
                                     self.policy.tiers["standard"])

    def weight(self, tenant: str) -> float:
        return self.policy.tenant_weights.get(tenant,
                                              self.policy.default_weight)

    # -- lifecycle ----------------------------------------------------------
    def register(self, req) -> None:
        """Track an admitted request (engine calls at submit/adopt)."""
        with self._lock:
            self._inflight[req.id] = req.tenant

    def forget(self, req) -> None:
        """Evict a retired request's entry. Idempotent."""
        with self._lock:
            self._inflight.pop(req.id, None)

    # -- service-rate observations (engine feeds measured step times) -------
    def observe_prefill(self, tokens: int, seconds: float) -> None:
        if tokens <= 0 or seconds <= 0:
            return
        with self._lock:
            per = seconds / tokens
            old = self._ewma_prefill_s
            self._ewma_prefill_s = per if old is None \
                else old + self.ALPHA * (per - old)

    def observe_decode(self, tokens: int, seconds: float) -> None:
        if tokens <= 0 or seconds <= 0:
            return
        with self._lock:
            per = seconds / tokens
            old = self._ewma_decode_s
            self._ewma_decode_s = per if old is None \
                else old + self.ALPHA * (per - old)

    def estimate_service_s(self, req) -> Optional[float]:
        """Predicted seconds to run ``req`` to completion starting now;
        None while the scheduler is cold (no observations yet)."""
        with self._lock:
            return self._estimate_locked(req)

    def _estimate_locked(self, req) -> Optional[float]:
        if self._ewma_prefill_s is None or self._ewma_decode_s is None:
            return None
        return (len(req.prompt) * self._ewma_prefill_s
                + req.max_new * self._ewma_decode_s)

    # -- the three decisions ------------------------------------------------
    def select(self, pending: List, now: float) -> Tuple[Optional[object],
                                                         List]:
        """Pick the next request to prefill from ``pending`` and name the
        ones to shed. Returns ``(choice, shed)``: ``choice`` is None when
        nothing survives shedding; every request in ``shed`` should be
        finished with :meth:`shed_error` by the caller. The winner is NOT
        charged here — the caller commits it with :meth:`charge` once it
        actually secures a decode slot. A saturated engine re-selects
        every scheduler turn; charging on selection would advance the
        winning tenant's pass without serving it, scrambling fair share
        exactly when contention makes it matter."""
        with self._lock:
            shed, live = [], []
            for r in pending:
                if (r.deadline is not None
                        and (est := self._estimate_locked(r)) is not None
                        and now + est * self.policy.shed_margin > r.deadline):
                    shed.append(r)
                else:
                    live.append(r)
            self.sheds += len(shed)
            if not live:
                return None, shed
            floor = min(self._pass.values()) if self._pass else 0.0
            best = min(live, key=lambda r: (
                self.tier(r).rank,
                self._pass.get(r.tenant, floor),
                r.t_submit, r.id))
            return best, shed

    def charge(self, req) -> None:
        """Commit a :meth:`select` winner: advance its tenant's stride
        pass by ``prompt_tokens / weight`` (a new tenant enters at the
        current pass floor, not at zero, so it cannot monopolize on
        arrival) and count the pick. Call exactly once per admitted
        request. Admission bills the PROMPT only — decode work is billed
        as it is actually served via :meth:`charge_tokens`, so a
        speculative engine's accepted multi-token turns (and early
        cancels/expiries) charge for real tokens delivered, not for the
        ``max_new`` the request merely asked for."""
        with self._lock:
            floor = min(self._pass.values()) if self._pass else 0.0
            t = req.tenant
            self._pass[t] = (self._pass.get(t, floor)
                             + len(req.prompt) / self.weight(t))
            self.picks += 1

    def charge_tokens(self, tenant: str, tokens: int) -> None:
        """Advance ``tenant``'s stride pass by ``tokens / weight`` for
        decode tokens actually DELIVERED (the engine calls this per emit
        with the accepted count — one per plain decode turn, up to
        ``k + 1`` per speculative verify turn). Keeps fair share honest
        under speculation: a tenant whose prompts draft well is billed
        for every token it receives, not one unit per turn."""
        if tokens <= 0:
            return
        with self._lock:
            floor = min(self._pass.values()) if self._pass else 0.0
            self._pass[tenant] = (self._pass.get(tenant, floor)
                                  + tokens / self.weight(tenant))

    def shed_error(self, req, now: float) -> ShedError:
        est = self.estimate_service_s(req)
        return ShedError(
            f"request {req.id} (tenant={req.tenant!r}, "
            f"priority={req.priority!r}) shed: estimated service "
            f"{est:.3f}s cannot meet deadline in "
            f"{max(req.deadline - now, 0.0):.3f}s")

    def pick_victim(self, running: List, incoming) -> Optional[object]:
        """Among ``running`` requests (occupying decode slots), the one to
        preempt so ``incoming`` can run — or None when preemption is off,
        ``incoming``'s tier doesn't preempt, or no preemptible
        lower-priority victim exists. Prefers the lowest-priority tier,
        then the youngest request (least sunk work to re-park)."""
        if not self.policy.preemption or not self.tier(incoming).preempts:
            return None
        rank_in = self.tier(incoming).rank
        victims = [r for r in running
                   if self.tier(r).preemptible
                   and self.tier(r).rank > rank_in]
        if not victims:
            return None
        return max(victims, key=lambda r: (self.tier(r).rank,
                                           r.t_submit, r.id))

    def note_preempt(self) -> None:
        with self._lock:
            self.preemptions += 1

    def note_resume(self) -> None:
        with self._lock:
            self.resumes += 1

    # -- introspection / handoff -------------------------------------------
    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "picks": self.picks, "sheds": self.sheds,
                "preemptions": self.preemptions, "resumes": self.resumes,
                "inflight": len(self._inflight),
                "tenants_seen": len(self._pass),
                "decode_ms_per_token": None if self._ewma_decode_s is None
                else self._ewma_decode_s * 1e3,
                "prefill_ms_per_token": None if self._ewma_prefill_s is None
                else self._ewma_prefill_s * 1e3,
            }

    def export_state(self) -> Dict[str, object]:
        """Fair-share passes + rate estimates, for drain/adopt handoff so
        a successor replica doesn't restart cold (and doesn't reset a
        flooding tenant's pass back to the floor)."""
        with self._lock:
            return {"pass": dict(self._pass),
                    "ewma_decode_s": self._ewma_decode_s,
                    "ewma_prefill_s": self._ewma_prefill_s}

    def load_state(self, state: Dict[str, object]) -> None:
        with self._lock:
            self._pass.update(state.get("pass") or {})
            if state.get("ewma_decode_s") is not None:
                self._ewma_decode_s = float(state["ewma_decode_s"])
            if state.get("ewma_prefill_s") is not None:
                self._ewma_prefill_s = float(state["ewma_prefill_s"])
