"""Telemetry-driven autoscaler: close the loop from the observability
plane back to live elasticity.

The controller consumes the same snapshot the Prometheus endpoint serves
(``observability.exporter.collect_snapshot()`` / ``get_serving_stats()``)
— TTFT p99, queue-wait p99, mean slot occupancy — compares them against
SLO targets, and drives two actuators:

* :meth:`ElasticRun.request_resize` — grow/shrink the data-parallel mesh
  at the next step boundary (training-side capacity);
* a *respawn* callable — serving-side replica scaling, expected to wrap
  the engine ``drain()`` -> successor ``adopt()`` handoff so no in-flight
  request drops while capacity changes.

Control discipline, because flapping replicas are worse than slow ones:
a scale-up needs ``breach_ticks`` CONSECUTIVE breached observations, a
scale-down needs ``relax_ticks`` consecutive calm ones (asymmetric on
purpose — scale up eagerly, down reluctantly), and every actuation arms a
``cooldown_s`` dead time during which decisions are recorded but not
acted on. ``dry_run=True`` turns the whole controller into a decision
recorder: :meth:`Autoscaler.step` still returns what it *would* do (the
decision table the guard tests assert against synthetic histograms) but
never touches an actuator.

:meth:`step` is a pure function of (stats, now, internal counters) —
feed it synthetic stats dicts and a fake clock to unit-test any scenario.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

__all__ = ["AutoscalePolicy", "Autoscaler"]


@dataclass(frozen=True)
class AutoscalePolicy:
    """SLO targets and control knobs. The latency targets default to the
    ``interactive`` tier objective from :data:`~mxtpu.sched.policy.
    DEFAULT_TIERS` — the strictest tier is the one worth scaling for.
    ``occupancy_high``/``occupancy_low`` bracket mean decode-slot
    utilization: above the high mark capacity is the bottleneck even if
    latency still holds; below the low mark capacity is wasted."""
    ttft_p99_slo_ms: float = 250.0
    queue_wait_p99_slo_ms: float = 100.0
    occupancy_high: float = 0.90
    occupancy_low: float = 0.30
    breach_ticks: int = 3
    relax_ticks: int = 6
    cooldown_s: float = 30.0
    min_replicas: int = 1
    max_replicas: int = 8

    def __post_init__(self):
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if self.breach_ticks < 1 or self.relax_ticks < 1:
            raise ValueError("breach/relax ticks must be >= 1")


class Autoscaler:
    """One controller instance. ``elastic`` (an ``ElasticRun``) and/or
    ``respawn`` (``callable(target_replicas)``) are the actuators; with
    neither — or with ``dry_run=True`` — decisions are only recorded.
    Drive it by calling :meth:`step` on whatever cadence the deployment
    scrapes metrics (it is cheap; every call appends one decision to the
    bounded ``decisions`` ring)."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None, *,
                 elastic=None, respawn: Optional[Callable] = None,
                 replicas: Optional[int] = None, dry_run: bool = False):
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.elastic = elastic
        self.respawn = respawn
        self.dry_run = bool(dry_run)
        self.replicas = int(replicas if replicas is not None
                            else self.policy.min_replicas)
        self._breach = 0   # consecutive breached observations
        self._calm = 0     # consecutive calm observations
        self._cooldown_until = 0.0
        self.decisions: deque = deque(maxlen=256)

    # -- signal extraction --------------------------------------------------
    @staticmethod
    def _serving(stats: Dict) -> Dict:
        """Accept either a full ``collect_snapshot()`` document or a bare
        ``get_serving_stats()`` dict."""
        inner = stats.get("serving")
        return inner if isinstance(inner, dict) else stats

    def signals(self, stats: Dict) -> Dict[str, Optional[float]]:
        s = self._serving(stats)
        pick = lambda k: (float(s[k]) if isinstance(s.get(k), (int, float))
                          else None)
        return {"ttft_p99_ms": pick("ttft_ms_p99"),
                "queue_wait_p99_ms": pick("queue_wait_ms_p99"),
                "occupancy": pick("slot_occupancy")}

    def _classify(self, sig: Dict[str, Optional[float]]) -> Optional[str]:
        """'breach' / 'calm' / None (not enough signal to say either)."""
        p = self.policy
        ttft, qw, occ = (sig["ttft_p99_ms"], sig["queue_wait_p99_ms"],
                         sig["occupancy"])
        if ((ttft is not None and ttft > p.ttft_p99_slo_ms)
                or (qw is not None and qw > p.queue_wait_p99_slo_ms)
                or (occ is not None and occ > p.occupancy_high)):
            return "breach"
        # calm needs POSITIVE evidence of headroom, not just absent breach
        if occ is None:
            return None
        if occ < p.occupancy_low \
                and (ttft is None or ttft < 0.5 * p.ttft_p99_slo_ms) \
                and (qw is None or qw < 0.5 * p.queue_wait_p99_slo_ms):
            return "calm"
        return None

    # -- the control step ---------------------------------------------------
    def step(self, stats: Dict, now: float) -> Dict[str, object]:
        """One control tick. Returns the decision record (also appended
        to ``decisions``): ``action`` in {'scale_up', 'scale_down',
        'hold'}, the breached/calm streaks, the target replica count, and
        whether an actuator was actually driven."""
        p = self.policy
        sig = self.signals(stats)
        verdict = self._classify(sig)
        if verdict == "breach":
            self._breach += 1
            self._calm = 0
        elif verdict == "calm":
            self._calm += 1
            self._breach = 0
        else:
            self._breach = 0
            self._calm = 0

        action, reason = "hold", verdict or "no-signal"
        target = self.replicas
        if now < self._cooldown_until:
            reason = f"cooldown ({self._cooldown_until - now:.1f}s left)"
        elif self._breach >= p.breach_ticks and target < p.max_replicas:
            action, target = "scale_up", target + 1
            reason = (f"{self._breach} consecutive SLO breaches "
                      f"(ttft={sig['ttft_p99_ms']}, "
                      f"queue_wait={sig['queue_wait_p99_ms']}, "
                      f"occupancy={sig['occupancy']})")
        elif self._calm >= p.relax_ticks and target > p.min_replicas:
            action, target = "scale_down", target - 1
            reason = f"{self._calm} consecutive calm observations"

        actuated = False
        if action != "hold":
            self._breach = 0
            self._calm = 0
            self._cooldown_until = now + p.cooldown_s
            if not self.dry_run:
                actuated = self._actuate(target)
            self.replicas = target
        decision = {"t": now, "action": action, "reason": reason,
                    "target": target, "signals": sig,
                    "dry_run": self.dry_run, "actuated": actuated}
        self.decisions.append(decision)
        return decision

    def _actuate(self, target: int) -> bool:
        did = False
        if self.elastic is not None:
            # don't stack a second resize on one the run hasn't served yet
            if not getattr(self.elastic, "pending_resize", False):
                self.elastic.request_resize(target)
                did = True
        if self.respawn is not None:
            self.respawn(target)
            did = True
        return did

    def decision_table(self) -> List[Dict[str, object]]:
        """The recorded decisions, oldest first (bounded ring)."""
        return list(self.decisions)
