"""Traffic-replay traces: seeded multi-tenant arrival processes.

The millions-of-users scenario is not one queue of uniform arrivals, so
the ``bench.py traffic`` leg (and any load test) drives the engine from a
:class:`TrafficTrace` built here: a deterministic, seeded list of
:class:`TrafficRequest` with realistic shapes —

* **arrival processes** — ``poisson`` (memoryless baseline), ``bursty``
  (Poisson base load with periodic high-rate bursts: the thundering-herd
  shape that exposes queue-wait and shedding), ``diurnal`` (sinusoidal
  rate over the trace span, thinned from a peak-rate Poisson: the
  day/night curve the autoscaler must track), and ``heavy_tail``
  (bursty arrivals + Pareto-distributed decode lengths: a few huge batch
  requests that monopolize slots unless the scheduler preempts);
* **multi-tenant populations** — each :class:`TenantProfile` contributes
  a fixed share of arrivals with its own priority tier, deadline budget,
  and a *shared token prefix* (the system-prompt shape the radix
  ``PrefixCache`` exploits — replays hit the cache exactly as production
  would).

Everything is derived from one ``random.Random(seed)``: the same (kind,
seed, knobs) always yields byte-identical traces, so bench numbers are
comparable across runs and schedulers can be A/B'd on the *same* traffic.
No jax imports — building a trace is free.
"""

from __future__ import annotations

import math
import random
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["TrafficRequest", "TenantProfile", "TrafficTrace", "make_trace",
           "KINDS"]

KINDS = ("poisson", "bursty", "diurnal", "heavy_tail")


@dataclass(frozen=True)
class TrafficRequest:
    """One scripted arrival: submit ``prompt`` at ``t`` seconds after
    replay start, on behalf of ``tenant`` at ``priority``, asking for
    ``max_new`` tokens within ``deadline_s`` (None = no deadline)."""
    t: float
    tenant: str
    priority: str
    prompt: Tuple[int, ...]
    max_new: int
    deadline_s: Optional[float]


@dataclass(frozen=True)
class TenantProfile:
    """One tenant's slice of the traffic mix. ``share`` weights how many
    arrivals it receives; ``prefix_len`` tokens are drawn ONCE per tenant
    and shared by all its prompts (prefix-cache-hittable), followed by
    ``suffix_len`` fresh tokens per request."""
    name: str
    priority: str = "standard"
    share: float = 1.0
    prefix_len: int = 32
    suffix_len: int = 8
    max_new: int = 16
    deadline_s: Optional[float] = None


@dataclass(frozen=True)
class TrafficTrace:
    kind: str
    seed: int
    duration_s: float
    requests: Tuple[TrafficRequest, ...]
    prefixes: Dict[str, Tuple[int, ...]] = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.requests)


def _poisson_arrivals(rng: random.Random, rate: float,
                      duration: float) -> List[float]:
    out, t = [], 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration:
            return out
        out.append(t)


def _thin(rng: random.Random, arrivals: List[float], accept) -> List[float]:
    """Keep each arrival with probability ``accept(t)`` (Lewis thinning —
    turns a peak-rate Poisson stream into any rate(t) <= peak)."""
    return [t for t in arrivals if rng.random() < accept(t)]


def _arrival_times(kind: str, rng: random.Random, rate: float,
                   duration: float) -> List[float]:
    if kind == "poisson":
        return _poisson_arrivals(rng, rate, duration)
    if kind in ("bursty", "heavy_tail"):
        # steady base load at rate/2 plus 4x-rate bursts covering the
        # middle fifth of each duration/3 window — overlapping arrivals
        # stack, which is the point
        base = _poisson_arrivals(rng, max(rate / 2, 1e-9), duration)
        burst = _poisson_arrivals(rng, rate * 4, duration)
        period = duration / 3.0
        burst = [t for t in burst if 0.4 <= (t % period) / period < 0.6]
        return sorted(base + burst)
    if kind == "diurnal":
        # one full sinusoidal "day" across the trace, floor 10% of peak
        peak = _poisson_arrivals(rng, rate * 2, duration)
        return _thin(rng, peak, lambda t: 0.1 + 0.9 * (
            0.5 - 0.5 * math.cos(2 * math.pi * t / duration)))
    raise ValueError(f"unknown trace kind {kind!r}; one of {KINDS}")


def _pareto_len(rng: random.Random, floor: int, cap: int,
                alpha: float = 1.3) -> int:
    """Heavy-tailed length in [floor, cap]: most requests near the floor,
    a rare few near the cap (the slot-monopolizing shape)."""
    x = floor * (1.0 - rng.random()) ** (-1.0 / alpha)
    return int(min(cap, max(floor, round(x))))


def make_trace(kind: str = "bursty", seed: int = 0, *,
               rate: float = 8.0, duration_s: float = 4.0,
               vocab: int = 256,
               tenants: Sequence[TenantProfile] = (),
               heavy_tail_cap: int = 96) -> TrafficTrace:
    """Build a deterministic trace: ``rate`` is the nominal aggregate
    arrivals/s (each kind shapes it differently), ``tenants`` the
    population mix (default: one standard-tier tenant). Token ids are
    drawn uniformly from ``[1, vocab)`` (0 is reserved so a BOS/pad id
    never collides with drawn content)."""
    if kind not in KINDS:
        raise ValueError(f"unknown trace kind {kind!r}; one of {KINDS}")
    # zlib.crc32, NOT hash(): str hashes are salted per process
    # (PYTHONHASHSEED), which would make "the same seed" yield a
    # different trace every run and turn the bench ratchet into noise
    key = f"{seed}|{kind}|{round(rate * 1e6)}|{round(duration_s * 1e6)}"
    rng = random.Random(zlib.crc32(key.encode()))
    if not tenants:
        tenants = (TenantProfile("default"),)
    tok = lambda: rng.randrange(1, max(vocab, 2))
    prefixes = {p.name: tuple(tok() for _ in range(p.prefix_len))
                for p in tenants}
    shares = [max(p.share, 0.0) for p in tenants]
    times = _arrival_times(kind, rng, rate, duration_s)
    reqs = []
    for t in times:
        p = rng.choices(tenants, weights=shares)[0]
        prompt = prefixes[p.name] + tuple(tok() for _ in range(p.suffix_len))
        max_new = p.max_new if kind != "heavy_tail" \
            else _pareto_len(rng, p.max_new, heavy_tail_cap)
        reqs.append(TrafficRequest(t=t, tenant=p.name, priority=p.priority,
                                   prompt=prompt, max_new=max_new,
                                   deadline_s=p.deadline_s))
    return TrafficTrace(kind=kind, seed=seed, duration_s=duration_s,
                        requests=tuple(reqs), prefixes=prefixes)
