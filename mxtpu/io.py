"""Data iterators — parity with ``python/mxnet/io.py`` (DataIter/DataBatch/DataDesc,
NDArrayIter, CSVIter, MNISTIter, ResizeIter, PrefetchingIter) and the C++ iterator
framework of ``src/io/`` (SURVEY.md §2.4: layered decorators — batching, shuffle,
prefetch).

Host pipeline is numpy/threads; the device boundary is one ``nd.array`` per batch.
"""

from __future__ import annotations

import os
import queue
import threading
from collections import namedtuple
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import ndarray as nd
from .ndarray.ndarray import NDArray

DataDesc = namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])
DataDesc.__new__.__defaults__ = (np.float32, "NCHW")


class DataBatch:
    def __init__(self, data, label=None, pad: int = 0, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    def __init__(self, batch_size: int = 0):
        self.batch_size = batch_size

    def reset(self):
        pass

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(), self.getpad(),
                             self.getindex())
        raise StopIteration

    def iter_next(self) -> bool:
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self) -> int:
        return 0

    @property
    def provide_data(self) -> List[DataDesc]:
        raise NotImplementedError

    @property
    def provide_label(self) -> List[DataDesc]:
        raise NotImplementedError


def _init_data(data, allow_empty: bool, default_name: str):
    if data is None:
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{i if i else ''}" if len(data) > 1 else default_name: d
                for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        arr = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        out.append((k, arr))
    return out


class NDArrayIter(DataIter):
    """In-memory iterator (io.py NDArrayIter: pad/discard/roll_over last-batch)."""

    def __init__(self, data, label=None, batch_size: int = 1, shuffle: bool = False,
                 last_batch_handle: str = "pad", data_name: str = "data",
                 label_name: str = "softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.last_batch_handle = last_batch_handle
        self.shuffle = shuffle
        self.cursor = -batch_size
        self._shuffled_idx = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self._shuffled_idx)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self._shuffled_idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self) -> bool:
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for _, arr in arrays:
            end = self.cursor + self.batch_size
            if end <= self.num_data:
                idx = self._shuffled_idx[self.cursor:end]
                out.append(nd.array(arr[idx]))
            else:  # pad by wrapping
                idx = np.concatenate([self._shuffled_idx[self.cursor:],
                                      self._shuffled_idx[:end - self.num_data]])
                out.append(nd.array(arr[idx]))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self) -> int:
        end = self.cursor + self.batch_size
        return max(0, end - self.num_data)


class CSVIter(DataIter):
    """CSV-backed iterator (src/io/iter_csv.cc parity)."""

    def __init__(self, data_csv: str, data_shape, label_csv: Optional[str] = None,
                 label_shape=(1,), batch_size: int = 1, round_batch: bool = True):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        self._inner_data = data.reshape((-1,) + tuple(data_shape))
        label = (np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
                 if label_csv else np.zeros((len(self._inner_data), 1), np.float32))
        self._inner = NDArrayIter(self._inner_data, label.squeeze(-1) if
                                  label.shape[-1] == 1 else label, batch_size,
                                  last_batch_handle="pad" if round_batch else "discard",
                                  label_name="label")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class LibSVMIter(DataIter):
    """LibSVM-format iterator producing CSR data batches
    (src/io/iter_libsvm.cc parity): lines are ``label idx:val idx:val ...``
    (indices 0-based like the reference default). ``data_shape`` is the
    feature-vector length; optional ``label_libsvm`` reads multi-output labels
    from a second libsvm file."""

    def __init__(self, data_libsvm: str, data_shape, batch_size: int = 1,
                 label_libsvm: Optional[str] = None, label_shape=(1,),
                 round_batch: bool = True):
        super().__init__(batch_size)
        self._num_features = int(data_shape[0] if isinstance(
            data_shape, (tuple, list)) else data_shape)
        self._labels, self._rows = self._parse(data_libsvm)
        if label_libsvm:
            self._labels = self._parse_labels(label_libsvm, label_shape)
        self._round = round_batch
        self._validate()
        self.reset()

    @staticmethod
    def _parse(path):
        labels, rows = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = []
                for t in parts[1:]:
                    i, v = t.split(":")
                    row.append((int(i), float(v)))
                rows.append(row)
        return np.asarray(labels, np.float32), rows

    def _validate(self):
        bad = max((j for row in self._rows for j, _ in row), default=-1)
        if bad >= self._num_features:
            raise ValueError(
                f"libsvm feature index {bad} >= data_shape {self._num_features}")

    @staticmethod
    def _parse_labels(path, label_shape):
        """External label file: either plain values per line (dense labels)
        or sparse idx:val rows (iter_libsvm.cc label_libsvm semantics)."""
        width = int(label_shape[0] if isinstance(label_shape, (tuple, list))
                    else label_shape)
        out = []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                row = np.zeros((width,), np.float32)
                if any(":" in t for t in parts):
                    for t in parts:
                        if ":" in t:
                            i, v = t.split(":")
                            row[int(i)] = float(v)
                else:
                    vals = [float(t) for t in parts]
                    row[:len(vals)] = vals
                out.append(row)
        dense = np.asarray(out, np.float32)
        return dense[:, 0] if width == 1 else dense

    def reset(self):
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._num_features))]

    @property
    def provide_label(self):
        lab = np.asarray(self._labels)
        shape = (self.batch_size,) if lab.ndim == 1 else \
            (self.batch_size,) + lab.shape[1:]
        return [DataDesc("softmax_label", shape)]

    def next(self) -> DataBatch:
        from .ndarray import sparse as _sparse
        n = len(self._rows)
        if self._cursor >= n:
            raise StopIteration
        idxs = list(range(self._cursor, min(self._cursor + self.batch_size, n)))
        pad = self.batch_size - len(idxs)
        if pad and not self._round:
            raise StopIteration
        idxs += idxs[-1:] * pad  # pad by repeating (round_batch)
        values, col_idx, indptr = [], [], [0]
        for i in idxs:
            for j, v in self._rows[i]:
                col_idx.append(j)
                values.append(v)
            indptr.append(len(values))
        data = _sparse.CSRNDArray(
            np.asarray(values, np.float32), np.asarray(col_idx, np.int64),
            np.asarray(indptr, np.int64),
            (self.batch_size, self._num_features))
        label = NDArray(np.asarray(self._labels[idxs]))
        self._cursor += self.batch_size
        return DataBatch(data=[data], label=[label], pad=pad)


class MNISTIter(DataIter):
    """MNIST iterator (src/io/iter_mnist.cc parity): flat=True → (N,784)."""

    def __init__(self, image: str = "", label: str = "", batch_size: int = 128,
                 shuffle: bool = True, flat: bool = False, seed: int = 0,
                 silent: bool = False, synthetic: bool = False, **kwargs):
        super().__init__(batch_size)
        if image and os.path.exists(image) or (image and os.path.exists(image + ".gz")):
            from .gluon.data.vision.datasets import _read_idx_images, _read_idx_labels
            imgs = _read_idx_images(image).astype(np.float32) / 255.0
            lbls = _read_idx_labels(label).astype(np.float32)
        else:
            # synthetic fallback (zero-egress env): LEARNABLE digit surrogates —
            # each class is a distinct bright patch location + noise, so the
            # canonical train_mnist flows actually converge on it
            rs = np.random.RandomState(seed or 42)
            n = 1024
            lbls = rs.randint(0, 10, (n,)).astype(np.float32)
            imgs = rs.rand(n, 28, 28, 1).astype(np.float32) * 0.3
            for i, c in enumerate(lbls.astype(int)):
                r0, c0 = 2 + (c // 5) * 12, 2 + (c % 5) * 5
                imgs[i, r0:r0 + 8, c0:c0 + 4, 0] += 0.7
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.transpose(0, 3, 1, 2)  # NCHW
        self._inner = NDArrayIter(imgs, lbls, batch_size, shuffle=shuffle)

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (io.py ResizeIter)."""

    def __init__(self, data_iter: DataIter, size: int, reset_internal: bool = True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label


class _PrefetchGen:
    """One producer lifetime: the thread is handed THIS object's queue and
    stop flag, so a straggler that outlives a ``reset()`` (join timeout while
    blocked in the backing iterator) can only ever see its own abandoned
    queue — it can neither hang on nor leak stale batches into the
    replacement generation (the old implementation cleared the shared stop
    flag and swapped ``self._queue``, so a timed-out producer woke up
    pointing at the NEW queue)."""

    __slots__ = ("queue", "stop", "thread", "error")

    def __init__(self, prefetch: int):
        self.queue: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self.stop = threading.Event()
        self.thread = None
        self.error: Optional[BaseException] = None

    def put(self, item) -> bool:
        """Stop-aware put: False once this generation is abandoned."""
        while not self.stop.is_set():
            try:
                self.queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False


class PrefetchingIter(DataIter):
    """Double-buffered producer thread (io.py PrefetchingIter ≈ iter_prefetcher.h).

    Exceptions in the producer are re-raised at next() — the reference's
    exception-propagation contract (docs/architecture/exception_handling.md).
    The exception is additionally latched on the generation, so it surfaces
    even when the queue handoff is lost (e.g. the producer died while its
    queue was full and the consumer only polls afterwards).
    """

    def __init__(self, iters, rename_data=None, rename_label=None, prefetch: int = 2):
        iters = iters if isinstance(iters, (list, tuple)) else [iters]
        assert len(iters) == 1, "single backing iter supported"
        super().__init__(iters[0].batch_size)
        self.iter = iters[0]
        self._prefetch = prefetch
        self._gen: Optional[_PrefetchGen] = None

    def _producer(self, gen: _PrefetchGen):
        try:
            src = iter(self.iter)  # adapters may reset in __iter__
            while not gen.stop.is_set():
                try:
                    batch = next(src)
                except StopIteration:
                    break
                if not gen.put(("data", batch)):
                    return
        except Exception as e:  # latch + propagate to consumer at next()
            gen.error = e
            gen.put(("error", e))
            return
        gen.put(("end", None))

    def _ensure(self) -> _PrefetchGen:
        if self._gen is None:
            gen = _PrefetchGen(self._prefetch)
            gen.thread = threading.Thread(target=self._producer, args=(gen,),
                                          daemon=True)
            gen.thread.start()
            self._gen = gen
        return self._gen

    def reset(self):
        gen, self._gen = self._gen, None
        if gen is not None:
            # abandon the generation BEFORE touching the backing iterator, or
            # a blocked put would keep draining the freshly-reset iter; the
            # stop flag stays set forever, so even a join timeout cannot
            # produce a straggler that touches the next generation
            gen.stop.set()
            try:  # wake a put blocked on a full queue
                gen.queue.get_nowait()
            except queue.Empty:
                pass
            if gen.thread is not None:
                gen.thread.join(timeout=10)
        self.iter.reset()

    def next(self):
        gen = self._ensure()
        while True:
            try:
                kind, payload = gen.queue.get(timeout=0.1)
                break
            except queue.Empty:
                if gen.error is not None:
                    raise gen.error
                if gen.thread is not None and not gen.thread.is_alive():
                    raise RuntimeError(
                        "PrefetchingIter producer thread died without "
                        "delivering a batch or an exception")
        if kind == "error":
            raise payload
        if kind == "end":
            raise StopIteration
        return payload

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label


def ImageRecordIter(path_imgrec: str, data_shape, batch_size: int,
                    label_width: int = 1, shuffle: bool = False,
                    preprocess_threads: int = 4, prefetch_buffer: int = 2,
                    rand_crop: bool = False, rand_mirror: bool = False,
                    mean_r: float = 0, mean_g: float = 0, mean_b: float = 0,
                    std_r: float = 1, std_g: float = 1, std_b: float = 1,
                    resize: int = 0, dtype: str = "float32",
                    ctx=None, device_feed: Optional[bool] = None,
                    **kwargs) -> DataIter:
    """ImageRecordIter parity (iter_image_recordio_2.cc): RecordIO → threaded decode/
    augment → NCHW batches, wrapped in a prefetcher.

    ``dtype='uint8'`` emits raw NCHW uint8 batches (no normalize) — the
    feed-to-accelerator layout where normalization runs on-device and the
    wire carries 1 byte/px.

    The reference's ``prefetch_buffer``/``preprocess_threads`` knobs also
    parameterize the device boundary: the returned iterator advertises them
    (``device_feed_depth``) so ``Module.fit``'s implicit ``DeviceFeed`` wrap
    prefetches ``prefetch_buffer`` batches device-resident with no code
    changes. Pass ``ctx=`` (a Context/device/mesh) or ``device_feed=True``
    to get the wrapped pipeline directly."""
    from .image import ImageIter
    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b], np.float32)
    std = None
    if (std_r, std_g, std_b) != (1, 1, 1):
        std = np.array([std_r, std_g, std_b], np.float32)
    it = ImageIter(batch_size, data_shape, label_width, path_imgrec=path_imgrec,
                   shuffle=shuffle, resize=resize, rand_crop=rand_crop,
                   rand_mirror=rand_mirror, mean=mean, std=std,
                   preprocess_threads=preprocess_threads, dtype=dtype)
    out = PrefetchingIter(_ImageIterAdapter(it, batch_size),
                          prefetch=prefetch_buffer)
    # knob propagation into the DeviceFeed wrapper (maybe_device_feed reads
    # device_feed_depth; preprocess_threads is advertised for introspection)
    out.device_feed_depth = prefetch_buffer
    out.preprocess_threads = preprocess_threads
    if ctx is not None or device_feed:
        from .device_feed import DeviceFeed
        return DeviceFeed(out, depth=prefetch_buffer, placement=ctx)
    return out


class _ImageIterAdapter(DataIter):
    def __init__(self, it, batch_size):
        super().__init__(batch_size)
        self._it = it

    def reset(self):
        self._it.reset()

    def next(self):
        return next(self._it)

    def __iter__(self):
        self._it.reset()
        return self._it
