"""Monitor — per-block output/weight/gradient spying, capability parity with
``python/mxnet/monitor.py:33-85`` (+ ``ExecuteMonCallback``,
graph_executor.cc:1563).

The reference installs a C callback on every executor op; here ``install``
walks a Gluon block tree and registers forward hooks that capture each
sub-block's output under its qualified name. Weights and gradients are read
from ``collect_params`` at ``toc`` time. Capture is eager-mode: inside a
``hybridize()``d/compiled graph intermediate arrays are tracers and are
skipped (the compiled graph has no per-op boundaries to spy on — same reason
the reference's monitor only sees executor-level ops)."""

from __future__ import annotations

import math
import re
from typing import Callable, List, Optional, Tuple

import numpy as np

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _is_concrete(arr) -> bool:
    import jax.core
    raw = arr.data if isinstance(arr, NDArray) else arr
    return not isinstance(raw, jax.core.Tracer)


class Monitor:
    """Monitor outputs, weights, and gradients for debugging (monitor.py:33).

    ``interval``: batches between collections. ``stat_func``: NDArray -> stat
    (default |x|_2 / sqrt(size)). ``pattern``: regex over tensor names
    ('.*output' → outputs only, '.*weight' → weights, '.*grad' → gradients).
    """

    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        if stat_func is None:
            def asum_stat(x):
                import jax.numpy as jnp
                if getattr(x, "stype", "default") != "default":
                    raw = x.data.data      # sparse: stats over stored values
                else:
                    raw = x.data if isinstance(x, NDArray) else x
                return float(jnp.linalg.norm(raw.astype(jnp.float32).ravel())
                             / math.sqrt(max(raw.size, 1)))
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, object]] = []
        self.step = 0
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self._blocks: List = []

    # -- installation ------------------------------------------------------
    def install(self, block):
        """Register capture hooks over the block tree (executor
        set_monitor_callback parity)."""
        if any(b is block for b in self._blocks):
            return
        self._blocks.append(block)

        def walk(b, prefix):
            for name, child in b._children.items():
                qual = f"{prefix}{name}"
                child.register_forward_hook(self._mk_hook(qual))
                walk(child, qual + ".")

        block.register_forward_hook(self._mk_hook(getattr(block, "prefix", "")
                                                  .rstrip("_") or "net"))
        walk(block, "")

    def _mk_hook(self, qual: str):
        def hook(blk, args, out):
            if not self.activated:
                return
            outs = out if isinstance(out, (list, tuple)) else [out]
            for i, o in enumerate(outs):
                if not isinstance(o, NDArray) or not _is_concrete(o):
                    continue
                name = f"{qual}_output" if len(outs) == 1 else \
                    f"{qual}_output{i}"
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(o)))
        return hook

    # -- per-batch protocol (tic/toc, monitor.py:85-140) --------------------
    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, object]]:
        if not self.activated:
            return []
        self.activated = False
        for block in self._blocks:
            for name, p in block.collect_params().items():
                if p._data is None:
                    continue
                if self.re_prog.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(p.data())))
                gname = name + "_grad"
                if p._data._grad is not None and self.re_prog.match(gname):
                    self.queue.append((self.step, gname,
                                       self.stat_func(p.grad())))
        res = self.queue
        self.queue = []
        if self.sort:
            res = sorted(res, key=lambda t: t[1])
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")
