"""AttrScope — ambient attributes for symbol construction (``mx.AttrScope``,
python/mxnet/attribute.py parity).

The reference's flagship use is ``ctx_group`` model-parallel placement:

    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(...)

and bind-time ``group2ctx`` maps groups to devices (graph_executor.cc:408
PlaceDevice inserting _CrossDeviceCopy). On TPU the placement capability maps
to sharding: annotate parameters via ``DataParallelTrainer(param_shardings=…)``
and GSPMD places the compute — there is no cross-device copy node to insert.
AttrScope itself is kept at full fidelity: scoped attrs are merged into every
node created inside the scope (user attrs use the reference's ``__name__``
mangling, so they serialize with the graph, round-trip through JSON, and are
visible to ``Symbol.attr``/``attr_dict`` — e.g. for a sharding policy keyed on
``__ctx_group__``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "apply", "current"]

_state = threading.local()


class AttrScope:
    """Context manager attaching attributes to symbols created in scope.

    Attribute values must be strings (reference attribute.py:40 enforces this
    so graphs serialize portably). Names are mangled to ``__name__`` like the
    reference's AttrScope.get, keeping user attrs disjoint from op config.
    """

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise ValueError(
                    f"AttrScope value for {k!r} must be a string, got "
                    f"{type(v).__name__}")
        self._attrs = {f"__{k}__": v for k, v in kwargs.items()}
        self._prev: Optional[Dict[str, str]] = None

    def __enter__(self) -> "AttrScope":
        self._prev = getattr(_state, "scope_attrs", None)
        merged = dict(self._prev or {})
        merged.update(self._attrs)
        _state.scope_attrs = merged
        return self

    def __exit__(self, *exc) -> None:
        _state.scope_attrs = self._prev
        self._prev = None


def current() -> Dict[str, str]:
    """The ambient attr dict new symbol nodes inherit ({} outside any scope)."""
    return getattr(_state, "scope_attrs", None) or {}


def apply(attr: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Ambient scope attrs merged under explicitly-given ones (explicit wins).
    The single precedence rule every symbol-construction site routes through."""
    merged = dict(current())
    if attr:
        merged.update(attr)
    return merged
