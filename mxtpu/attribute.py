"""AttrScope — ambient attributes for symbol construction (``mx.AttrScope``,
python/mxnet/attribute.py parity).

The reference's flagship use is ``ctx_group`` model-parallel placement:

    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(...)

and bind-time ``group2ctx`` maps groups to devices (graph_executor.cc:408
PlaceDevice inserting _CrossDeviceCopy). On TPU the placement capability maps
to sharding: annotate parameters via ``DataParallelTrainer(param_shardings=…)``
and GSPMD places the compute — there is no cross-device copy node to insert.
AttrScope itself is kept at full fidelity: scoped attrs are merged into every
node created inside the scope under their PLAIN names (reference
attribute.py:52 ``AttrScope.get`` stores ``kwargs`` unmangled), so they
serialize with the graph, round-trip through JSON, and are visible to
``Symbol.attr('ctx_group')``/``attr_dict``/``list_attr`` exactly as
reference-style migration code expects.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "apply", "current"]

_state = threading.local()


class AttrScope:
    """Context manager attaching attributes to symbols created in scope.

    Attribute values must be strings (reference attribute.py:40 enforces this
    so graphs serialize portably). Names are stored unmangled, matching the
    reference's AttrScope.get — ``sym.attr('ctx_group')`` must find them.
    """

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise ValueError(
                    f"AttrScope value for {k!r} must be a string, got "
                    f"{type(v).__name__}")
        self._attrs = dict(kwargs)
        self._prev: Optional[Dict[str, str]] = None

    def __enter__(self) -> "AttrScope":
        self._prev = getattr(_state, "scope_attrs", None)
        merged = dict(self._prev or {})
        merged.update(self._attrs)
        _state.scope_attrs = merged
        return self

    def __exit__(self, *exc) -> None:
        _state.scope_attrs = self._prev
        self._prev = None


def current() -> Dict[str, str]:
    """The ambient attr dict new symbol nodes inherit ({} outside any scope)."""
    return getattr(_state, "scope_attrs", None) or {}


def apply(attr: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Ambient scope attrs merged under explicitly-given ones (explicit wins).
    The single precedence rule every symbol-construction site routes through."""
    merged = dict(current())
    if attr:
        merged.update(attr)
    return merged
