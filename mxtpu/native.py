"""ctypes binding + on-demand build of the native IO library (native/mxtpu_io.cc).

The reference's data-pipeline hot loops are C++ (RecordIO chunk parse + OMP JPEG
decode + batch assembly, src/io/iter_image_recordio_2.cc:50-149). Here the same
host-side loops — RecordIO indexing, positioned parallel record reads, and the fused
uint8-HWC → float32-CHW normalize that feeds ``device_put`` — are C++ with std::thread
pools, built once with g++ at first use and bound via ctypes (no pybind11 in the
image; the ABI is 5 flat C functions).

Everything degrades gracefully: ``available()`` is False when no compiler exists and
callers fall back to numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "mxtpu_io.cc")
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libmxtpu_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def compile_shared(src: str, lib_path: str, extra_flag_sets=((),),
                   timeout: int = 180) -> bool:
    """g++ -O3 -shared -fPIC a native source into a .so, rebuilt only when the
    source is newer than the artifact. ``extra_flag_sets`` are tried in order
    until one compiles (feature-gated variants first, bare fallback last).
    Shared by every on-demand native build (IO lib here, C ABI in capi.py)."""
    if os.path.exists(lib_path) and \
            os.path.getmtime(lib_path) >= os.path.getmtime(src):
        return True
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            src, "-o", lib_path]
    for extra in extra_flag_sets:
        try:
            subprocess.run(base + list(extra), check=True, capture_output=True,
                           timeout=timeout)
            return True
        except (OSError, subprocess.SubprocessError):
            continue
    return False


def _build() -> bool:
    # jpeg support is optional: hosts without libjpeg dev files still get the
    # RecordIO/normalize kernels (jpeg entry points report failure -> PIL path)
    return compile_shared(_SRC, _LIB_PATH,
                          (["-DMXTPU_HAVE_JPEG", "-ljpeg"], []), timeout=120)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC) or not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        lib.rio_index.restype = ctypes.c_int64
        lib.rio_index.argtypes = [ctypes.c_char_p, i64p, i64p, ctypes.c_int64]
        lib.rio_read_batch.restype = ctypes.c_int
        lib.rio_read_batch.argtypes = [ctypes.c_char_p, i64p, i64p, i64p,
                                       ctypes.c_int64, ctypes.c_char_p,
                                       ctypes.c_int]
        lib.nhwc_u8_to_nchw_f32.restype = None
        lib.nhwc_u8_to_nchw_f32.argtypes = [
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int]
        lib.jpeg_dims.restype = ctypes.c_int
        lib.jpeg_dims.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int64)]
        lib.jpeg_decode.restype = ctypes.c_int
        lib.jpeg_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            ctypes.c_int64]
        lib.decode_augment_batch.restype = ctypes.c_int
        lib.decode_augment_batch.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_int]
        lib.mxtpu_io_abi_version.restype = ctypes.c_int
        if lib.mxtpu_io_abi_version() != 3:
            return None  # stale artifact: degrade gracefully, don't crash
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def rio_index(path: str, max_records: int = 1 << 22
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Scan a RecordIO file in C; returns (payload_offsets, payload_sizes)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable (no g++?)")
    offsets = np.empty(max_records, np.int64)
    sizes = np.empty(max_records, np.int64)
    n = lib.rio_index(path.encode(), offsets, sizes, max_records)
    if n == -1:
        raise IOError(f"rio_index: cannot open {path}")
    if n == -2:
        raise IOError(f"rio_index: corrupt RecordIO magic in {path}")
    return offsets[:n].copy(), sizes[:n].copy()


def rio_read_batch(path: str, offsets: np.ndarray, sizes: np.ndarray,
                   num_threads: int = 0) -> Tuple[bytes, np.ndarray]:
    """Positioned parallel reads of many records; returns (buffer, out_offsets)
    where record i is buffer[out_offsets[i]:out_offsets[i]+sizes[i]]."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native IO library unavailable")
    offsets = np.ascontiguousarray(offsets, np.int64)
    sizes = np.ascontiguousarray(sizes, np.int64)
    out_offsets = np.zeros(len(sizes), np.int64)
    np.cumsum(sizes[:-1], out=out_offsets[1:]) if len(sizes) > 1 else None
    total = int(sizes.sum())
    buf = ctypes.create_string_buffer(total)
    rc = lib.rio_read_batch(path.encode(), offsets, sizes, out_offsets,
                            len(sizes), buf, num_threads)
    if rc != 0:
        raise IOError(f"rio_read_batch failed on {path}")
    return buf.raw, out_offsets


def jpeg_decode(buf: bytes) -> Optional[np.ndarray]:
    """Decode a JPEG byte buffer to an HWC uint8 RGB array via libjpeg
    (iter_image_recordio_2.cc:138-149 decode-loop parity). Returns None when
    the native library is unavailable or the buffer fails to decode (caller
    falls back to PIL). The ctypes call releases the GIL, so callers'
    thread pools parallelize decode across cores."""
    lib = _load()
    if lib is None:
        return None
    h = ctypes.c_int64()
    w = ctypes.c_int64()
    c = ctypes.c_int64()
    if lib.jpeg_dims(buf, len(buf), ctypes.byref(h), ctypes.byref(w),
                     ctypes.byref(c)) != 0:
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    if lib.jpeg_decode(buf, len(buf), out, out.size) != 0:
        return None
    return out


def decode_augment_batch(blob: bytes, offsets: np.ndarray, sizes: np.ndarray,
                         hw: Tuple[int, int], mean=None, std=None,
                         rand_crop: bool = False, rand_mirror: bool = False,
                         seed: int = 0, out_dtype: str = "float32",
                         num_threads: int = 0) -> Optional[np.ndarray]:
    """One threaded C pass per batch: JPEG decode -> crop -> mirror ->
    [normalize ->] NCHW into a preallocated slab (iter_image_recordio_2.cc
    ParseChunk parity). Returns None when the native path can't serve the
    batch (no library, non-JPEG record, image smaller than target) — the
    caller falls back to the per-image path."""
    lib = _load()
    if lib is None:
        return None
    H, W = int(hw[0]), int(hw[1])
    n = len(sizes)
    u8 = out_dtype == "uint8"
    out = np.empty((n, 3, H, W), np.uint8 if u8 else np.float32)
    _m = None if mean is None else np.ascontiguousarray(mean, np.float32)
    _s = None if std is None else np.ascontiguousarray(std, np.float32)
    rc = lib.decode_augment_batch(
        blob, np.ascontiguousarray(offsets, np.int64),
        np.ascontiguousarray(sizes, np.int64), n, H, W,
        None if _m is None else _m.ctypes.data_as(ctypes.c_void_p),
        None if _s is None else _s.ctypes.data_as(ctypes.c_void_p),
        1 if rand_crop else 0, 1 if rand_mirror else 0,
        ctypes.c_uint64(seed & (2**64 - 1)), 1 if u8 else 0,
        out.ctypes.data_as(ctypes.c_void_p), num_threads)
    if rc != 0:
        return None
    return out


def nhwc_u8_to_nchw_f32(batch: np.ndarray, mean=None, std=None,
                        scale255: bool = False, num_threads: int = 0
                        ) -> np.ndarray:
    """Fused (x[/255] - mean)/std + HWC→CHW for an N×H×W×C uint8 batch."""
    lib = _load()
    if lib is None:  # numpy fallback, same math
        out = batch.astype(np.float32)
        if scale255:
            out /= 255.0
        if mean is not None:
            out -= np.asarray(mean, np.float32)
        if std is not None:
            out /= np.asarray(std, np.float32)
        return np.ascontiguousarray(out.transpose(0, 3, 1, 2))
    batch = np.ascontiguousarray(batch, np.uint8)
    n, h, w, c = batch.shape
    out = np.empty((n, c, h, w), np.float32)
    mp = None if mean is None else \
        np.ascontiguousarray(mean, np.float32).ctypes.data_as(ctypes.c_void_p)
    sp = None if std is None else \
        np.ascontiguousarray(std, np.float32).ctypes.data_as(ctypes.c_void_p)
    # keep the arrays alive across the call
    _m = None if mean is None else np.ascontiguousarray(mean, np.float32)
    _s = None if std is None else np.ascontiguousarray(std, np.float32)
    mp = None if _m is None else _m.ctypes.data_as(ctypes.c_void_p)
    sp = None if _s is None else _s.ctypes.data_as(ctypes.c_void_p)
    lib.nhwc_u8_to_nchw_f32(batch, out, mp, sp, n, h, w, c,
                            1 if scale255 else 0, num_threads)
    return out
