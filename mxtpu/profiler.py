"""Profiler — parity with ``src/profiler/`` + ``python/mxnet/profiler.py``
(SURVEY.md §5): set_config/set_state/dump, pause/resume, Domain/Task/Frame/
Event/Counter/Marker objects, chrome://tracing output.

This module is the user-facing FACADE over :mod:`mxtpu.observability`:

* the span recorder (``observability.tracer``) captures the unified step
  timeline — ``step/compile``, ``step/execute``, ``feed/transfer``,
  ``feed/stall``, ``comm/exchange``, ``ckpt/*`` — on per-thread rings, each
  span mirrored into ``jax.profiler.TraceAnnotation`` so XLA device traces
  (XPlane dirs from ``set_state('run')``, openable in Perfetto) line up with
  the framework spans;
* ``dump()``/``dumps()`` serialize it to valid chrome://tracing JSON
  (``observability.export``), with pid/tid rows per thread (main,
  feed-producer, ckpt-writer) — ``dump(finished=True)`` freezes the snapshot
  so repeated dumps are idempotent rather than accumulating;
* MFU accounting (``observability.flops``) feeds ``get_mfu_stats()`` —
  steps/s, p50/p99 step latency, FLOPs/step, MFU vs the chip's documented
  peak;
* every subsystem counter surface (``record_*`` / ``get_*_stats`` /
  ``reset_*`` for checkpoint, device-feed, comm, sanitizer) is re-exported
  unchanged from ``observability.metrics``.

Tracing is opt-in — ``MXTPU_TRACE=1`` (the ``MXNET_PROFILER_AUTOSTART``
analogue) or ``profiler.set_state('run')`` — and the off path is a single
bool test per instrumentation point. The legacy Domain/Task/Counter/Marker
objects keep their original always-on local event list (``_state['events']``)
AND emit real spans onto the unified timeline when tracing is armed.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax

from .observability import export as _export
from .observability import flops as _flops
from .observability import histogram as _hist
from .observability import tracer as _tracer
from .observability.metrics import (  # noqa: F401  (re-exported surface)
    _stats_lock,
    add_commit_hook,
    get_checkpoint_stats, get_comm_stats, get_feed_stats,
    get_memory_stats, get_quant_stats, get_resilience_stats,
    get_router_stats, get_sanitizer_stats, get_sched_stats,
    get_serving_stats,
    record_checkpoint_commit, record_checkpoint_restore,
    record_checkpoint_save, record_checkpoint_shard_write,
    record_collective, record_comm_step,
    record_feed_consume, record_feed_prefetch, record_feed_resident,
    record_feed_transfer, record_memory_stats,
    record_quant_error, record_quant_matmuls, record_quant_range,
    record_resilience, record_router, record_sanitizer, record_sched,
    record_serving, record_serving_occupancy, record_tenant,
    reset_checkpoint_stats, reset_comm_stats, reset_feed_stats,
    reset_memory_stats, reset_quant_stats, reset_resilience_stats,
    reset_router_stats, reset_sanitizer_stats, reset_sched_stats,
    reset_serving_stats,
    sanitizer_violations, set_feed_depth,
)

# MFU/step-latency surface (observability.flops is the store)
get_mfu_stats = _flops.get_mfu_stats
record_step_time = _flops.record_step
reset_step_times = _flops.reset_steps

# streaming latency histograms (observability.histogram is the store)
get_histogram = _hist.get_histogram
get_histogram_stats = _hist.get_histogram_stats
reset_histograms = _hist.reset_histograms

_state = {"config": {"filename": "profile.json", "profile_all": False},
          "running": False, "dir": None, "events": [], "paused": False}

# dump(finished=True) freezes its payload here so repeated finished dumps
# rewrite the SAME file content instead of re-collecting (and duplicating)
# whatever was recorded since — cleared by set_state('run') / reset_trace()
_final = {"payload": None}


def set_config(**kwargs):
    """profiler.set_config parity (filename, profile_{symbolic,imperative,memory,api},
    aggregate_stats…); unknown knobs are accepted and recorded."""
    with _stats_lock:
        _state["config"].update(kwargs)


def set_state(state: str = "stop", profile_process: str = "worker"):
    """'run' arms the unified span recorder AND an XLA device trace
    (``jax.profiler.start_trace`` XPlane dir next to the configured
    filename); 'stop' closes both. ``set_config(xplane=False)`` keeps the
    framework spans without the device-trace dir (cheap mode — what
    ``MXTPU_TRACE=1`` uses)."""
    if state == "run" and not _state["running"]:
        _tracer.start()
        with _stats_lock:
            _final["payload"] = None          # a new run unfreezes the dump
        if not _state["config"].get("xplane", True):
            with _stats_lock:
                _state["running"] = True
            return
        out_dir = os.path.splitext(_state["config"].get("filename", "profile.json"))[0] \
            + "_trace"
        with _stats_lock:
            _state["dir"] = out_dir
        jax.profiler.start_trace(out_dir)
        with _stats_lock:
            _state["running"] = True
    elif state == "stop":
        if _state["running"]:
            if _state["config"].get("xplane", True):
                jax.profiler.stop_trace()
            with _stats_lock:
                _state["running"] = False
        _tracer.stop()
        with _stats_lock:
            # explicit stop cancels pause-resume
            _state.pop("resume_running", None)


def pause(profile_process: str = "worker"):
    """Suspend collection (c_api MXProfilePause parity): custom events and
    framework spans stop recording and the device trace is closed until
    resume()."""
    if _state["paused"]:
        return
    with _stats_lock:
        _state["paused"] = True
    _tracer.pause()
    if _state["running"]:
        if _state["config"].get("xplane", True):
            jax.profiler.stop_trace()
        with _stats_lock:
            _state["running"] = False
            _state["resume_running"] = True


def resume(profile_process: str = "worker"):
    if not _state["paused"]:
        return
    with _stats_lock:
        _state["paused"] = False
        restart = _state.pop("resume_running", False)
        if restart:
            _state["segment"] = _state.get("segment", 0) + 1
            out_dir = f"{_state['dir']}_resume{_state['segment']}"
            _state["dir"] = out_dir  # dump() must point at the live trace dir
    _tracer.resume()
    if restart:
        if _state["config"].get("xplane", True):
            jax.profiler.start_trace(out_dir)
        with _stats_lock:
            _state["running"] = True


def reset_trace():
    """Drop every recorded span/event and unfreeze a finished dump (tests,
    back-to-back bench legs)."""
    _tracer.reset()
    with _stats_lock:
        _state["events"] = []
        _final["payload"] = None


def dump(finished: bool = True, profile_process: str = "worker"):
    """Stop tracing and write the chrome://tracing JSON (one ``pid`` with a
    named ``tid`` row per instrumented thread). ``finished=True`` (the
    reference default) freezes the payload: calling ``dump(finished=True)``
    again rewrites the identical file instead of duplicating events recorded
    since; ``finished=False`` writes a live snapshot without freezing."""
    if _state["running"]:
        set_state("stop")
    with _stats_lock:
        fname = _state["config"].get("filename", "profile.json")
        legacy = list(_state["events"])
        xdir = _state["dir"]
        payload = _final["payload"] if finished else None
    if payload is None:
        payload = _export.chrome_trace(legacy_events=legacy, xplane_dir=xdir)
        if finished:
            with _stats_lock:
                if _final["payload"] is None:
                    _final["payload"] = payload
                else:
                    payload = _final["payload"]   # lost the freeze race
    _export.write_chrome_trace(fname, payload)
    return fname


def get_summary(sort_by: str = "total") -> str:
    """Aggregate-stats table (MXAggregateProfileStatsPrint / aggregate_stats.cc
    parity): per-name count, total/avg/min/max duration over every recorded
    span — the unified tracer's rings AND the legacy custom-object events."""
    with _stats_lock:
        legacy = list(_state["events"])
    stats = _export.aggregate(_export.collect_events(legacy))
    key = {"total": lambda kv: -kv[1][1], "count": lambda kv: -kv[1][0],
           "avg": lambda kv: -(kv[1][1] / max(kv[1][0], 1)),
           "name": lambda kv: kv[0]}[sort_by]
    lines = [f"{'Name':<40s}{'Count':>8s}{'Total(ms)':>12s}{'Avg(ms)':>10s}"
             f"{'Min(ms)':>10s}{'Max(ms)':>10s}"]
    lines.append("-" * len(lines[0]))
    for name, (cnt, tot, mn, mx) in sorted(stats.items(), key=key):
        lines.append(f"{name:<40s}{cnt:>8d}{tot:>12.3f}{tot/cnt:>10.3f}"
                     f"{mn:>10.3f}{mx:>10.3f}")
    return "\n".join(lines)


def dumps(reset: bool = False) -> str:
    """Aggregate table when set_config(aggregate_stats=True) (reference
    profiler.dumps), raw chrome-trace JSON otherwise — traceEvents now
    includes the unified span store alongside every subsystem stats block."""
    if _state["config"].get("aggregate_stats"):
        out = get_summary()
    else:
        with _stats_lock:
            legacy = list(_state["events"])
        out = json.dumps({"traceEvents": _export.collect_events(legacy),
                          "compileCaches": get_compile_stats(),
                          "checkpoint": get_checkpoint_stats(),
                          "deviceFeed": get_feed_stats(),
                          "comm": get_comm_stats(),
                          "memory": get_memory_stats(),
                          "sanitizer": get_sanitizer_stats(),
                          "resilience": get_resilience_stats(),
                          "serving": get_serving_stats(),
                          "histograms": _hist.get_histogram_stats(),
                          "mfu": get_mfu_stats()})
    if reset:
        reset_trace()
    return out


# ---------------------------------------------------------------------------
# compile-cache observability (step_cache registry)
# ---------------------------------------------------------------------------


def get_compile_stats() -> dict:
    """Per-cache {hits, traces, retraces} for every signature cache in the
    framework (fused training step, CachedOp/hybridize, symbol Executor
    backward, DataParallelTrainer step). The TPU-native analogue of the
    reference's engine-bulk forensics: a fixed-shape training loop should
    show exactly one trace and N-1 hits — anything else is a retrace leak."""
    from .step_cache import snapshot
    return snapshot()


def reset_compile_stats(name: Optional[str] = None):
    """Zero one named cache's counters (or all). Tests and epoch-boundary
    accounting use this; the caches themselves are untouched."""
    from .step_cache import reset_stats
    reset_stats(name)


def compile_cache_summary() -> str:
    """Human-readable compile-cache table (pairs with get_summary()), plus
    the sanitizer counter line when a sanitized run recorded anything."""
    stats = get_compile_stats()
    lines = [f"{'Cache':<24s}{'Hits':>10s}{'Traces':>10s}{'Retraces':>10s}"]
    lines.append("-" * len(lines[0]))
    for name in sorted(stats):
        s = stats[name]
        lines.append(f"{name:<24s}{s['hits']:>10d}{s['traces']:>10d}"
                     f"{s['retraces']:>10d}")
    san = get_sanitizer_stats()
    if any(san.values()):
        lines.append(
            f"sanitizer: transfer-guards={san['transfer_guards']} "
            f"(trips {san['transfer_trips']}), "
            f"poisons={san['donation_poisons_armed']} "
            f"(trips {san['donation_trips']}), "
            f"retrace-escalations={san['retrace_escalations']}, "
            f"ownership={san['ownership_checks']} "
            f"(trips {san['ownership_trips']})")
    mem = get_memory_stats()
    if mem["param_bytes_per_device"] or mem["slot_bytes_per_device"]:
        lines.append(
            f"memory: zero-stage={mem['stage']} "
            f"(data×fsdp {mem['data_degree']}×{mem['fsdp_degree']}) "
            f"per-device params={mem['param_bytes_per_device']} "
            f"grads={mem['grad_bytes_per_device']} "
            f"slots={mem['slot_bytes_per_device']} B "
            f"(replicated: {mem['replicated_param_bytes']}/"
            f"{mem['replicated_grad_bytes']}/"
            f"{mem['replicated_slot_bytes']} B)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# custom profiling objects (Domain/Task/Frame/Event/Counter/Marker)
# ---------------------------------------------------------------------------


class Domain:
    def __init__(self, name: str):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Scoped:
    def __init__(self, domain: Optional[Domain], name: str):
        self.domain = domain
        self.name = name
        self._ann = None
        self._t0 = None

    def start(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter_ns()

    def stop(self):
        if self._ann is not None:
            t1 = time.perf_counter_ns()
            self._ann.__exit__(None, None, None)
            cat = self.domain.name if self.domain else "default"
            if not _state["paused"]:
                with _stats_lock:
                    _state["events"].append({
                        "name": self.name, "ph": "X", "ts": self._t0 / 1000,
                        "dur": (t1 - self._t0) / 1000,
                        "pid": 0, "tid": 0, "cat": cat})
                # mirror onto the unified timeline (real pid/tid row) when
                # the span recorder is armed
                _tracer.record_span(self.name, self._t0, t1 - self._t0,
                                    cat=cat)
            self._ann = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Scoped):
    pass


class Frame(_Scoped):
    pass


class Event(_Scoped):
    pass


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain, self.name = domain, name
        self.value = value or 0

    def set_value(self, value):
        self.value = value
        if not _state["paused"]:
            with _stats_lock:
                _state["events"].append({"name": self.name, "ph": "C",
                                         "ts": time.perf_counter_ns() / 1000,
                                         "pid": 0,
                                         "args": {self.name: value}})
            _tracer.counter(self.name, value,
                            cat=self.domain.name if self.domain
                            else "counters")

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


class Marker:
    def __init__(self, domain, name):
        self.domain, self.name = domain, name

    def mark(self, scope: str = "process"):
        if not _state["paused"]:
            with _stats_lock:
                _state["events"].append({"name": self.name, "ph": "i",
                                         "ts": time.perf_counter_ns() / 1000,
                                         "pid": 0, "s": scope[0]})
            _tracer.instant(self.name,
                            cat=self.domain.name if self.domain else "marker",
                            scope=scope[0])
