"""Profiler — parity with ``src/profiler/`` + ``python/mxnet/profiler.py``
(SURVEY.md §5): set_config/set_state/dump, pause/resume, Domain/Task/Frame/Event/
Counter/Marker objects, chrome://tracing output.

Backed by ``jax.profiler``: ``dump()`` produces a TensorBoard/XPlane trace directory
(openable in Perfetto — the modern chrome://tracing), and custom objects map onto
``jax.profiler.TraceAnnotation``/``StepTraceAnnotation``. Per-op granularity inside a
fused XLA program comes from XLA's own HLO-level annotations rather than engine-push
hooks (the reference hooks Engine::Push, profiler.h:256).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

import jax

_state = {"config": {"filename": "profile.json", "profile_all": False},
          "running": False, "dir": None, "events": [], "paused": False}

# THE module stats lock. Every stat dict here (_state events, _ckpt, _feed,
# _comm, _san) is bumped from more than one thread — the DeviceFeed producer
# (device_feed.py), the checkpoint writer (checkpoint/manager.py), and the
# main training thread — and read-modify-write pairs (total+last) tear
# without mutual exclusion. One lock, never held across a call that could
# re-acquire it (tpulint R004 is the static guard for this contract).
_stats_lock = threading.Lock()


def set_config(**kwargs):
    """profiler.set_config parity (filename, profile_{symbolic,imperative,memory,api},
    aggregate_stats…); unknown knobs are accepted and recorded."""
    with _stats_lock:
        _state["config"].update(kwargs)


def set_state(state: str = "stop", profile_process: str = "worker"):
    if state == "run" and not _state["running"]:
        out_dir = os.path.splitext(_state["config"].get("filename", "profile.json"))[0] \
            + "_trace"
        with _stats_lock:
            _state["dir"] = out_dir
        jax.profiler.start_trace(out_dir)
        with _stats_lock:
            _state["running"] = True
    elif state == "stop":
        if _state["running"]:
            jax.profiler.stop_trace()
            with _stats_lock:
                _state["running"] = False
        with _stats_lock:
            # explicit stop cancels pause-resume
            _state.pop("resume_running", None)


def pause(profile_process: str = "worker"):
    """Suspend collection (c_api MXProfilePause parity): custom events stop
    recording and the device trace is closed until resume()."""
    if _state["paused"]:
        return
    with _stats_lock:
        _state["paused"] = True
    if _state["running"]:
        jax.profiler.stop_trace()
        with _stats_lock:
            _state["running"] = False
            _state["resume_running"] = True


def resume(profile_process: str = "worker"):
    if not _state["paused"]:
        return
    with _stats_lock:
        _state["paused"] = False
        restart = _state.pop("resume_running", False)
        if restart:
            _state["segment"] = _state.get("segment", 0) + 1
            out_dir = f"{_state['dir']}_resume{_state['segment']}"
            _state["dir"] = out_dir  # dump() must point at the live trace dir
    if restart:
        jax.profiler.start_trace(out_dir)
        with _stats_lock:
            _state["running"] = True


def dump(finished: bool = True, profile_process: str = "worker"):
    """Stop tracing and write the chrome-tracing-compatible summary json."""
    if _state["running"]:
        set_state("stop")
    with _stats_lock:
        fname = _state["config"].get("filename", "profile.json")
        payload = {"traceEvents": list(_state["events"]),
                   "xplane_dir": _state["dir"],
                   "displayTimeUnit": "ms"}
    with open(fname, "w") as f:
        json.dump(payload, f)
    return fname


def get_summary(sort_by: str = "total") -> str:
    """Aggregate-stats table (MXAggregateProfileStatsPrint / aggregate_stats.cc
    parity): per-name count, total/avg/min/max duration over recorded events."""
    with _stats_lock:
        events = list(_state["events"])
    stats = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        s = stats.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
        dur = e.get("dur", 0.0) / 1000.0  # ms
        s[0] += 1
        s[1] += dur
        s[2] = min(s[2], dur)
        s[3] = max(s[3], dur)
    key = {"total": lambda kv: -kv[1][1], "count": lambda kv: -kv[1][0],
           "avg": lambda kv: -(kv[1][1] / max(kv[1][0], 1)),
           "name": lambda kv: kv[0]}[sort_by]
    lines = [f"{'Name':<40s}{'Count':>8s}{'Total(ms)':>12s}{'Avg(ms)':>10s}"
             f"{'Min(ms)':>10s}{'Max(ms)':>10s}"]
    lines.append("-" * len(lines[0]))
    for name, (cnt, tot, mn, mx) in sorted(stats.items(), key=key):
        lines.append(f"{name:<40s}{cnt:>8d}{tot:>12.3f}{tot/cnt:>10.3f}"
                     f"{mn:>10.3f}{mx:>10.3f}")
    return "\n".join(lines)


def dumps(reset: bool = False) -> str:
    """Aggregate table when set_config(aggregate_stats=True) (reference
    profiler.dumps), raw chrome-trace JSON otherwise."""
    if _state["config"].get("aggregate_stats"):
        out = get_summary()
    else:
        with _stats_lock:
            events = list(_state["events"])
        out = json.dumps({"traceEvents": events,
                          "compileCaches": get_compile_stats(),
                          "checkpoint": get_checkpoint_stats(),
                          "deviceFeed": get_feed_stats(),
                          "comm": get_comm_stats(),
                          "sanitizer": get_sanitizer_stats()})
    if reset:
        with _stats_lock:
            _state["events"] = []
    return out


# ---------------------------------------------------------------------------
# checkpoint observability (mxtpu.checkpoint manager counters)
# ---------------------------------------------------------------------------

_CKPT_ZERO = {"saves": 0, "commits": 0, "restores": 0,
              "committed_bytes": 0,
              "blocked_step_ms_total": 0.0, "blocked_step_ms_last": 0.0,
              "save_latency_ms_total": 0.0, "save_latency_ms_last": 0.0,
              "write_ms_last": 0.0,
              "shard_writes": 0, "shard_write_ms_last": 0.0}
_ckpt = dict(_CKPT_ZERO)


def record_checkpoint_save(blocked_ms: float):
    """Training-thread side of an async save: how long the step was blocked
    on the snapshot handoff (device→host DMA start + enqueue)."""
    with _stats_lock:
        _ckpt["saves"] += 1
        _ckpt["blocked_step_ms_last"] = blocked_ms
        _ckpt["blocked_step_ms_total"] += blocked_ms


def record_checkpoint_commit(write_ms: float, latency_ms: float, nbytes: int):
    """Writer-thread side: ``write_ms`` is the serialize+fsync+commit work,
    ``latency_ms`` the enqueue→commit wall time (queueing included),
    ``nbytes`` the committed payload size."""
    with _stats_lock:
        _ckpt["commits"] += 1
        _ckpt["write_ms_last"] = write_ms
        _ckpt["save_latency_ms_last"] = latency_ms
        _ckpt["save_latency_ms_total"] += latency_ms
        _ckpt["committed_bytes"] += int(nbytes)


def record_checkpoint_shard_write(write_ms: float):
    """Writer-thread side on ranks != 0: only this rank's shard write is
    measured — commit stats (count/bytes) belong to rank 0, which owns the
    rename and is the only rank that can see the final dir."""
    with _stats_lock:
        _ckpt["shard_writes"] += 1
        _ckpt["shard_write_ms_last"] = write_ms


def record_checkpoint_restore():
    with _stats_lock:
        _ckpt["restores"] += 1


def get_checkpoint_stats() -> dict:
    """Checkpoint counters (saves/commits/restores, committed bytes, save
    latency, blocked-step time) — the observability contract of the async
    checkpoint subsystem; bench.py's `checkpoint` scenario reads these."""
    with _stats_lock:
        return dict(_ckpt)


def reset_checkpoint_stats():
    with _stats_lock:
        _ckpt.update(_CKPT_ZERO)


# ---------------------------------------------------------------------------
# device-feed observability (mxtpu.device_feed input-pipeline counters)
# ---------------------------------------------------------------------------

_FEED_ZERO = {"batches_prefetched": 0, "batches_consumed": 0,
              "transfer_count": 0, "resident_skips": 0,
              "transfer_bytes": 0, "transfer_ms_total": 0.0,
              "stall_ms_total": 0.0, "stall_ms_last": 0.0,
              "queue_depth_max": 0, "feed_depth": 0}
_feed = dict(_FEED_ZERO)


def record_feed_transfer(nbytes: int, ms: float):
    """Producer-thread side: one array dispatched through the host→device
    boundary (``ms`` is the non-blocking dispatch wall time)."""
    with _stats_lock:
        _feed["transfer_count"] += 1
        _feed["transfer_bytes"] += int(nbytes)
        _feed["transfer_ms_total"] += ms


def record_feed_resident():
    """Producer-thread side: an array already committed with the target
    sharding was NOT re-transferred — the double-``device_put`` guard
    counter."""
    with _stats_lock:
        _feed["resident_skips"] += 1


def record_feed_prefetch(queue_depth: int):
    """Producer-thread side: one batch staged device-resident; samples the
    queue-depth high-water mark."""
    with _stats_lock:
        _feed["batches_prefetched"] += 1
        if queue_depth > _feed["queue_depth_max"]:
            _feed["queue_depth_max"] = queue_depth


def record_feed_consume(stall_ms: float):
    """Consumer-thread side: one batch taken; ``stall_ms`` is how long the
    step loop was blocked waiting on data (the input-stall metric)."""
    with _stats_lock:
        _feed["batches_consumed"] += 1
        _feed["stall_ms_last"] = stall_ms
        _feed["stall_ms_total"] += stall_ms


def set_feed_depth(depth: int):
    with _stats_lock:
        _feed["feed_depth"] = int(depth)


def get_feed_stats() -> dict:
    """Input-pipeline counters (input-stall ms, transfer bytes/ms, queue-depth
    high-water mark, batches prefetched vs consumed) — the observability
    contract of the device-feed pipeline. ``Speedometer`` prints these;
    ``bench.py input_pipeline`` reads them as the stall-fraction source of
    truth. Counters are monotone until :func:`reset_feed_stats`."""
    with _stats_lock:
        return dict(_feed)


def reset_feed_stats():
    """Zero the feed counters (tests, per-epoch accounting, bench legs)."""
    with _stats_lock:
        _feed.update(_FEED_ZERO)


# ---------------------------------------------------------------------------
# distributed-comm observability (ZeRO-1 / collectives counters)
# ---------------------------------------------------------------------------

_COMM_ZERO = {"steps": 0, "zero_steps": 0,
              "bytes_reduced": 0, "bytes_gathered": 0, "allreduce_bytes": 0,
              "bucket_count": 0, "shard_bytes_per_device": 0, "dp": 1,
              "collectives": 0, "collective_ms_total": 0.0,
              "collective_bytes": 0}
_comm = dict(_COMM_ZERO)


def record_comm_step(bytes_reduced: int = 0, bytes_gathered: int = 0,
                     bucket_count: int = 0, shard_bytes: int = 0,
                     dp: int = 1, allreduce_bytes: int = 0,
                     zero: bool = False):
    """One training step's gradient-exchange accounting (per-device bytes,
    analytic from the bucket layout and dp degree — ring collectives move
    (N-1)/N of the payload per device). The ZeRO path records reduce-scatter
    + all-gather legs; the replicated-psum path records the full all-reduce
    equivalent, so the two are directly comparable in ``bench.py zero_dp``."""
    with _stats_lock:
        _comm["steps"] += 1
        if zero:
            _comm["zero_steps"] += 1
        _comm["bytes_reduced"] += int(bytes_reduced)
        _comm["bytes_gathered"] += int(bytes_gathered)
        _comm["allreduce_bytes"] += int(allreduce_bytes)
        _comm["bucket_count"] = int(bucket_count)
        _comm["shard_bytes_per_device"] = int(shard_bytes)
        _comm["dp"] = int(dp)


def record_collective(ms: float, nbytes: int):
    """One host-blocking array-level collective (``parallel.collectives``
    cross-process exchange): measured wall ms + payload bytes."""
    with _stats_lock:
        _comm["collectives"] += 1
        _comm["collective_ms_total"] += ms
        _comm["collective_bytes"] += int(nbytes)


def get_comm_stats() -> dict:
    """Per-step comm counters (bytes reduced/gathered, bucket count, shard
    bytes per device, dp degree, measured collective ms) — the observability
    contract of the ZeRO-1 gradient path. ``Speedometer`` prints the per-step
    deltas; ``Module.fit`` logs them per epoch; ``bench.py zero_dp`` compares
    the ZeRO legs against the replicated all-reduce accounting."""
    with _stats_lock:
        return dict(_comm)


def reset_comm_stats():
    with _stats_lock:
        _comm.update(_COMM_ZERO)


# ---------------------------------------------------------------------------
# sanitizer observability (mxtpu.analysis.sanitize counters)
# ---------------------------------------------------------------------------

_SAN_ZERO = {"transfer_guards": 0, "transfer_trips": 0,
             "donation_poisons_armed": 0, "donation_trips": 0,
             "retrace_escalations": 0,
             "ownership_checks": 0, "ownership_trips": 0}
_san = dict(_SAN_ZERO)


def record_sanitizer(key: str, n: int = 1):
    """One sanitizer event (``mxtpu.analysis.sanitize``): guards armed and
    poisons planted count the coverage a sanitized run actually had; trips
    and escalations count violations (a clean run reports zero)."""
    with _stats_lock:
        _san[key] += int(n)


def get_sanitizer_stats() -> dict:
    """Sanitizer counters (transfer-guard arms/trips, donation poisons
    armed/tripped, retrace escalations, ownership assertions checked/
    tripped) — the observability contract of ``MXTPU_SANITIZE``.
    ``compile_cache_summary()`` prints them, ``Module.fit`` logs the
    per-epoch deltas, and ``bench.py --sanitize`` emits them as the
    ``"sanitizer"`` JSON block."""
    with _stats_lock:
        return dict(_san)


def sanitizer_violations(stats: Optional[dict] = None) -> int:
    """Total violations in a stats snapshot (0 for a clean sanitized run)."""
    s = stats if stats is not None else get_sanitizer_stats()
    return (s["transfer_trips"] + s["donation_trips"]
            + s["retrace_escalations"] + s["ownership_trips"])


def reset_sanitizer_stats():
    with _stats_lock:
        _san.update(_SAN_ZERO)


# ---------------------------------------------------------------------------
# compile-cache observability (step_cache registry)
# ---------------------------------------------------------------------------


def get_compile_stats() -> dict:
    """Per-cache {hits, traces, retraces} for every signature cache in the
    framework (fused training step, CachedOp/hybridize, symbol Executor
    backward, DataParallelTrainer step). The TPU-native analogue of the
    reference's engine-bulk forensics: a fixed-shape training loop should
    show exactly one trace and N-1 hits — anything else is a retrace leak."""
    from .step_cache import snapshot
    return snapshot()


def reset_compile_stats(name: Optional[str] = None):
    """Zero one named cache's counters (or all). Tests and epoch-boundary
    accounting use this; the caches themselves are untouched."""
    from .step_cache import reset_stats
    reset_stats(name)


def compile_cache_summary() -> str:
    """Human-readable compile-cache table (pairs with get_summary()), plus
    the sanitizer counter line when a sanitized run recorded anything."""
    stats = get_compile_stats()
    lines = [f"{'Cache':<24s}{'Hits':>10s}{'Traces':>10s}{'Retraces':>10s}"]
    lines.append("-" * len(lines[0]))
    for name in sorted(stats):
        s = stats[name]
        lines.append(f"{name:<24s}{s['hits']:>10d}{s['traces']:>10d}"
                     f"{s['retraces']:>10d}")
    san = get_sanitizer_stats()
    if any(san.values()):
        lines.append(
            f"sanitizer: transfer-guards={san['transfer_guards']} "
            f"(trips {san['transfer_trips']}), "
            f"poisons={san['donation_poisons_armed']} "
            f"(trips {san['donation_trips']}), "
            f"retrace-escalations={san['retrace_escalations']}, "
            f"ownership={san['ownership_checks']} "
            f"(trips {san['ownership_trips']})")
    return "\n".join(lines)


class Domain:
    def __init__(self, name: str):
        self.name = name

    def new_task(self, name):
        return Task(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _Scoped:
    def __init__(self, domain: Optional[Domain], name: str):
        self.domain = domain
        self.name = name
        self._ann = None
        self._t0 = None

    def start(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter_ns()

    def stop(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            if not _state["paused"]:
                with _stats_lock:
                    _state["events"].append({
                        "name": self.name, "ph": "X", "ts": self._t0 / 1000,
                        "dur": (time.perf_counter_ns() - self._t0) / 1000,
                        "pid": 0, "tid": 0,
                        "cat": self.domain.name if self.domain else "default"})
            self._ann = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Scoped):
    pass


class Frame(_Scoped):
    pass


class Event(_Scoped):
    pass


class Counter:
    def __init__(self, domain, name, value=None):
        self.domain, self.name = domain, name
        self.value = value or 0

    def set_value(self, value):
        self.value = value
        if not _state["paused"]:
            with _stats_lock:
                _state["events"].append({"name": self.name, "ph": "C",
                                         "ts": time.perf_counter_ns() / 1000,
                                         "pid": 0,
                                         "args": {self.name: value}})

    def increment(self, delta=1):
        self.set_value(self.value + delta)

    def decrement(self, delta=1):
        self.set_value(self.value - delta)


class Marker:
    def __init__(self, domain, name):
        self.domain, self.name = domain, name

    def mark(self, scope: str = "process"):
        if not _state["paused"]:
            with _stats_lock:
                _state["events"].append({"name": self.name, "ph": "i",
                                         "ts": time.perf_counter_ns() / 1000,
                                         "pid": 0, "s": scope[0]})
