"""Base types and small utilities shared across the framework.

Plays the role of the reference's ``include/mxnet/base.h`` + the pieces of dmlc-core the
Python frontend leans on (``dmlc::GetEnv`` env-var access, string/dtype utilities,
``registry.py`` generic registries — see SURVEY.md §2.7). No C ABI is needed at this
layer: the frontend talks to XLA through JAX directly.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Type

import jax.numpy as jnp
import numpy as np

__version__ = "0.1.0"

# ---------------------------------------------------------------------------
# dtype handling
# ---------------------------------------------------------------------------

#: Canonical dtype name → jnp dtype. Mirrors the reference's supported dtype set
#: (mshadow type enum used by ``infer_type``) plus bfloat16, which is the native
#: TPU compute dtype and therefore first-class here.
_DTYPE_MAP: Dict[str, Any] = {
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
}

_DTYPE_ID = {  # stable ids for serialization (matches mshadow enum where it exists)
    "float32": 0, "float64": 1, "float16": 2, "uint8": 3, "int32": 4,
    "int8": 5, "int64": 6, "bfloat16": 12, "bool": 7,
}
_ID_DTYPE = {v: k for k, v in _DTYPE_ID.items()}


def dtype_np(dtype) -> np.dtype:
    """Normalize a user dtype spec to a numpy dtype (bfloat16 via ml_dtypes)."""
    if dtype is None:
        return np.dtype("float32")
    if isinstance(dtype, str) and dtype in _DTYPE_MAP:
        return np.dtype(_DTYPE_MAP[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name if not isinstance(dtype, str) else dtype


def dtype_id(dtype) -> int:
    return _DTYPE_ID[dtype_name(dtype)]


def dtype_from_id(tid: int) -> str:
    return _ID_DTYPE[tid]


# ---------------------------------------------------------------------------
# environment variable catalog (dmlc::GetEnv equivalent; docs/faq/env_var.md parity)
# ---------------------------------------------------------------------------

_ENV_PREFIX = "MXTPU_"
_ENV_CATALOG: Dict[str, str] = {}


def getenv(name: str, default, doc: str = ""):
    """Read a framework env var (``MXTPU_*``), recording it in the catalog.

    The reference scatters ``dmlc::GetEnv("MXNET_…")`` at use sites and documents them in
    docs/faq/env_var.md; here every read self-registers so ``env_catalog()`` is always
    complete.
    """
    key = name if name.startswith(_ENV_PREFIX) else _ENV_PREFIX + name
    if doc:
        _ENV_CATALOG[key] = doc
    raw = os.environ.get(key)
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def env_catalog() -> Dict[str, str]:
    return dict(_ENV_CATALOG)


# ---------------------------------------------------------------------------
# generic name→object registry (python/mxnet/registry.py equivalent)
# ---------------------------------------------------------------------------

class Registry:
    """Name → class/function registry with alias support.

    Replaces both dmlc-core's C++ registry and ``python/mxnet/registry.py``'s
    ``get_register_func``/``get_create_func`` pattern with one small class.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._registry: Dict[str, Any] = {}

    def register(self, obj=None, *, name: Optional[str] = None, aliases: tuple = ()):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            self._registry[key] = o
            for a in aliases:
                self._registry[a.lower()] = o
            return o

        return _do if obj is None else _do(obj)

    def get(self, name: str):
        key = name.lower()
        if key not in self._registry:
            raise KeyError(f"{self.kind} {name!r} is not registered; known: {sorted(self._registry)}")
        return self._registry[key]

    def create(self, spec, **kwargs):
        """Create from a name, a (name, kwargs) pair, or pass through an instance."""
        if isinstance(spec, str):
            return self.get(spec)(**kwargs)
        return spec

    def __contains__(self, name: str) -> bool:
        return isinstance(name, str) and name.lower() in self._registry

    def keys(self):
        return sorted(self._registry)


def capture_init_spec(cls):
    """Wrap ``cls.__init__`` to record the outermost constructor call's
    ``(args, kwargs)`` on the instance as ``_init_spec`` — the parameter
    server's restricted wire format (``ps.serialize_optimizer``) re-creates
    objects from this spec instead of shipping pickle. Applied from
    ``__init_subclass__`` so every subclass is covered; the guard keeps inner
    ``super().__init__`` calls from overwriting the outermost spec."""
    import functools
    init = cls.__dict__.get("__init__")
    if init is None or getattr(init, "_captures_spec", False):
        return

    @functools.wraps(init)
    def wrapped(self, *args, **kwargs):
        outermost = not hasattr(self, "_init_spec")
        if outermost:
            self._init_spec = (args, dict(kwargs))
        init(self, *args, **kwargs)
        if outermost:
            # value snapshot of public attrs as __init__ left them — the wire
            # serializer diffs against this to detect post-construction
            # mutations its restricted format can't carry (shallow-copied so
            # later in-place dict/list edits are visible; spec-captured
            # sub-objects like lr_scheduler get a one-level vars snapshot,
            # since the wire re-creates them from their ctor spec and would
            # miss in-place edits)
            self._post_init_attrs = {
                k: _snap_value(v)
                for k, v in vars(self).items() if not k.startswith("_")}

    wrapped._captures_spec = True
    cls.__init__ = wrapped


class ObjSnap:
    """One-level value snapshot of a spec-captured sub-object (see
    ``capture_init_spec``): holds the object identity plus a copy of its
    public attrs at ``__init__`` time."""
    __slots__ = ("obj", "attrs")

    def __init__(self, obj, attrs):
        self.obj, self.attrs = obj, attrs


def _snap_value(v):
    if isinstance(v, (dict, list, set)):
        return v.copy()
    if hasattr(v, "_init_spec"):
        return ObjSnap(v, {k: (w.copy() if isinstance(w, (dict, list, set)) else w)
                           for k, w in vars(v).items() if not k.startswith("_")})
    return v


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

class MXTPUError(RuntimeError):
    """Framework-level error (the reference surfaces dmlc::Error through MXGetLastError)."""


class NotImplementedForSymbol(MXTPUError):
    """Raised when an NDArray-only dunder is used on a Symbol (reference
    ``base.py`` NotImplementedForSymbol; e.g. ``bool(sym)`` — comparison
    symbols build graph nodes, so truthiness must fail loudly)."""

    def __init__(self, function, alias=None, *args):
        name = getattr(function, "__name__", str(function))
        msg = f"Function {name}"
        if alias:
            msg += f" (namely operator '{alias}')"
        msg += " is not implemented for Symbol and only available in NDArray."
        super().__init__(msg)


def check(cond: bool, msg: str = "check failed"):
    if not cond:
        raise MXTPUError(msg)
