"""JIT layer — CachedOp (hybridize) and functional transforms.

Capability parity with the reference's two graph-execution engines (SURVEY.md §2.1):

* ``CachedOp`` (src/imperative/cached_op.{h,cc}) — Gluon ``hybridize()``: trace a
  Python forward once, re-run the compiled graph after. Here the trace IS ``jax.jit``:
  the imperative NDArray ops run on tracers transparently (they are jnp calls under the
  hood), so hybridizing is "run forward under jit, cache by input signature".
  The reference's knobs map as: ``static_alloc``/``static_shape`` → XLA buffer
  assignment (always on, accepted for API parity); per-shape retraces → the signature
  cache (the BucketingModule story); ``inline_limit`` → XLA inlining (N/A).
* ``GraphExecutor``'s passes (gradient, memory planning, device placement) are XLA's
  job; the *export* capability (symbol JSON + params, block.py:866 ``export``) maps to
  StableHLO serialization (``export_stablehlo``).

Mutation discipline: a traced forward may mutate state handles (BatchNorm running
stats). The trace detects which handles were written (their buffer became a tracer)
and turns them into extra outputs that are written back on every call — the functional
equivalent of the reference's aux-state arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import autograd, rng
from .ndarray.ndarray import NDArray
from .step_cache import cache_stats

__all__ = ["CachedOp", "jit", "grad", "value_and_grad", "export_stablehlo"]


class CachedOp:
    """Compile an NDArray-level callable; re-trace per input signature.

    ``fn(*args)`` takes NDArrays and may close over parameter/state NDArray handles
    (passed as ``params`` so tracing can substitute tracers and grads can flow).
    """

    def __init__(self, fn: Callable, params: Sequence[NDArray] = (),
                 static_alloc: bool = False, static_shape: bool = False,
                 donate_params: bool = False):
        self.fn = fn
        self.params: List[NDArray] = list(params)
        self.static_alloc = static_alloc  # API parity; XLA always plans statically
        self.static_shape = static_shape
        self._cache: Dict[tuple, dict] = {}
        self._stats = cache_stats("cached_op")

    # -- signature ---------------------------------------------------------
    @staticmethod
    def _shard_key(raw):
        # device placement/sharding is part of the compiled executable's
        # contract: params re-placed with new shardings (e.g. after a
        # DataParallelTrainer._collect) must invalidate the traced entry.
        # Shardings are hashable — no stringification on the hot path.
        return getattr(raw, "sharding", None)

    def _sig(self, args) -> tuple:
        return (
            tuple((a.shape, str(a.dtype), self._shard_key(a.data)) for a in args),
            tuple((p.shape, str(p.dtype), self._shard_key(p._data))
                  for p in self.params),
            autograd.is_training(),
        )

    # -- tracing -----------------------------------------------------------
    def _build(self, sig, args) -> dict:
        n_params = len(self.params)
        param_handles = self.params
        fn = self.fn
        mutated_idx: List[int] = []
        out_struct: dict = {}

        def pure(param_raws, input_raws, key):
            provider = rng.push_trace_provider(key)
            saved = [p._data for p in param_handles]
            try:
                for p, r in zip(param_handles, param_raws):
                    p._data = r
                    p._version += 1
                arg_handles = [NDArray(r) for r in input_raws]
                with autograd.pause(train_mode=autograd.is_training()):
                    result = fn(*arg_handles)
                single = not isinstance(result, (tuple, list))
                outs = [result] if single else list(result)
                out_struct["single"] = single
                raw_outs = [o.data for o in outs]
                # state write-back: params whose buffer was swapped during the trace
                mutated_idx.clear()
                mutated = []
                for i, (p, r) in enumerate(zip(param_handles, param_raws)):
                    if p._data is not r:
                        mutated_idx.append(i)
                        mutated.append(p._data)
                out_struct["n_keys"] = provider.count
                return tuple(raw_outs), tuple(mutated)
            finally:
                for p, s in zip(param_handles, saved):
                    p._data = s
                    p._version += 1
                rng.pop_trace_provider()

        jitted = jax.jit(pure)
        # prime the trace now so out_struct/mutated_idx are known
        key0 = rng.next_key()
        raw_outs, mutated = jitted([p.data for p in self.params],
                                   [a.data for a in args], key0)
        entry = {
            "jitted": jitted,
            "single": out_struct["single"],
            "mutated_idx": list(mutated_idx),
            "first": (raw_outs, mutated, key0),
        }
        self._cache[sig] = entry
        return entry

    def __call__(self, *args: NDArray):
        args = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
        sig = self._sig(args)
        entry = self._cache.get(sig)
        first = None
        if entry is None:
            self._stats.miss()
            entry = self._build(sig, args)
            raw_outs, mutated, key = entry.pop("first")
            first = True
        else:
            self._stats.hit()
            key = rng.next_key()
            raw_outs, mutated = entry["jitted"](
                [p.data for p in self.params], [a.data for a in args], key)

        outs = [NDArray(r) for r in raw_outs]

        if autograd.is_recording():
            jitted = entry["jitted"]
            n_params = len(self.params)
            fixed_key = key

            def pure_primary(*raws):
                o, _ = jitted(list(raws[:n_params]), list(raws[n_params:]), fixed_key)
                return tuple(o) if len(o) > 1 else o[0]

            autograd.record_custom_node(pure_primary, self.params + list(args), outs)

        # state write-back (aux mutation, e.g. BN moving stats)
        for i, m in zip(entry["mutated_idx"], mutated):
            self.params[i]._set_data(m)

        if entry["single"]:
            return outs[0]
        return tuple(outs)


def jit(fn: Callable, static_alloc: bool = False) -> Callable:
    """Functional convenience: hybridize a free function over NDArrays.

    Parameters are any NDArray leaves in args — no closure state support here; use
    CachedOp for stateful blocks.
    """
    op = CachedOp(fn, params=())
    return op


def _functionalize(fn: Callable):
    """Wrap an NDArray-level fn as a raw-array fn for jax transforms."""

    def raw_fn(*raws):
        outs = fn(*[NDArray(r) for r in raws])
        if isinstance(outs, (tuple, list)):
            return tuple(o.data for o in outs)
        return outs.data

    return raw_fn


def grad(fn: Callable, argnums=0) -> Callable:
    """Functional gradient transform over NDArray functions (composable — this is the
    higher-order escape hatch the imperative tape doesn't cover, jax.grad underneath)."""
    raw_fn = _functionalize(fn)
    gfn = jax.grad(raw_fn, argnums=argnums)

    def wrapped(*args):
        raws = [a.data if isinstance(a, NDArray) else jnp.asarray(a) for a in args]
        out = gfn(*raws)
        if isinstance(out, tuple):
            return tuple(NDArray(o) for o in out)
        return NDArray(out)

    return wrapped


def value_and_grad(fn: Callable, argnums=0) -> Callable:
    raw_fn = _functionalize(fn)
    vg = jax.value_and_grad(raw_fn, argnums=argnums)

    def wrapped(*args):
        raws = [a.data if isinstance(a, NDArray) else jnp.asarray(a) for a in args]
        v, g = vg(*raws)
        if isinstance(g, tuple):
            g = tuple(NDArray(x) for x in g)
        else:
            g = NDArray(g)
        return NDArray(v), g

    return wrapped


def export_stablehlo(fn: Callable, example_args: Sequence[NDArray]) -> str:
    """Serialize a traced computation to StableHLO text.

    Capability parity with symbol-JSON export (``Symbol.tojson`` symbol.py:1218 /
    ``HybridBlock.export`` block.py:866): a portable, inspectable compiled-graph
    artifact. StableHLO is the XLA-native exchange format.
    """
    raw_fn = _functionalize(fn)
    lowered = jax.jit(raw_fn).lower(*[a.data for a in example_args])
    return lowered.as_text()
