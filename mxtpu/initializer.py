"""Weight initializers — parity with ``python/mxnet/initializer.py`` (SURVEY.md §2.5).

Registry-backed so string specs work everywhere a reference API accepts them
(``net.initialize(init='xavier')``, ``Parameter(init=...)``).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import rng
from .base import Registry, dtype_np
from .ndarray.ndarray import NDArray

registry = Registry("initializer")
register = registry.register


class Initializer:
    """Base initializer. Subclasses implement ``_init_array(key, shape, dtype)``."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name_or_arr, arr: Optional[NDArray] = None):
        """Two calling conventions for parity: ``init(name, arr)`` (reference
        InitDesc protocol) or ``init(arr)``."""
        if arr is None:
            name, arr = "", name_or_arr
        else:
            name = str(name_or_arr)
        self.init_array(name, arr)
        return arr

    def init_array(self, name: str, arr: NDArray):
        lname = name.lower()
        if lname.endswith("bias") or lname.endswith("beta") or lname.endswith("running_mean"):
            arr._set_data(jnp.zeros(arr.shape, arr.dtype))
        elif lname.endswith("gamma") or lname.endswith("running_var"):
            arr._set_data(jnp.ones(arr.shape, arr.dtype))
        else:
            arr._set_data(self._init_array(rng.next_key(), arr.shape, arr.dtype))

    def _init_array(self, key, shape, dtype):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register(name="zeros", aliases=("zero",))
class Zero(Initializer):
    def _init_array(self, key, shape, dtype):
        return jnp.zeros(shape, dtype)


@register(name="ones", aliases=("one",))
class One(Initializer):
    def _init_array(self, key, shape, dtype):
        return jnp.ones(shape, dtype)


@register(name="constant")
class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        super().__init__(value=value)
        self.value = value

    def _init_array(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype)


@register(name="uniform")
class Uniform(Initializer):
    def __init__(self, scale: float = 0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_array(self, key, shape, dtype):
        return jax.random.uniform(key, shape, jnp.float32, -self.scale,
                                  self.scale).astype(dtype)


@register(name="normal")
class Normal(Initializer):
    def __init__(self, sigma: float = 0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_array(self, key, shape, dtype):
        return (self.sigma * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


def _fans(shape):
    if len(shape) < 2:
        return (shape[0] if shape else 1,) * 2
    hw = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * hw
    fan_out = shape[0] * hw
    return fan_in, fan_out


@register(name="xavier")
class Xavier(Initializer):
    """Glorot init (initializer.py Xavier): factor_type in/out/avg × uniform/gaussian."""

    def __init__(self, rnd_type: str = "uniform", factor_type: str = "avg",
                 magnitude: float = 3.0):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type, self.factor_type, self.magnitude = rnd_type, factor_type, magnitude

    def _init_array(self, key, shape, dtype):
        fan_in, fan_out = _fans(shape)
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[
            self.factor_type]
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            out = jax.random.uniform(key, shape, jnp.float32, -scale, scale)
        else:
            out = scale * jax.random.normal(key, shape, jnp.float32)
        return out.astype(dtype)


@register(name="msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type: str = "avg", slope: float = 0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register(name="orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale: float = 1.414, rand_type: str = "uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_array(self, key, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(key, (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        q = q.T if rows < cols else q
        return (self.scale * q[:rows, :cols]).reshape(shape).astype(dtype)


@register(name="bilinear")
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for Deconvolution-based UpSampling)."""

    def _init_array(self, key, shape, dtype):
        weight = np.zeros(shape, np.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(weight, dtype)


@register(name="lstmbias")
class LSTMBias(Initializer):
    """Forget-gate bias init (initializer.py LSTMBias): forget gate = forget_bias."""

    def __init__(self, forget_bias: float = 1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_array(self, key, shape, dtype):
        out = np.zeros(shape, np.float32)
        n = shape[0] // 4
        out[n:2 * n] = self.forget_bias  # gate order i,f,c,o
        return jnp.asarray(out, dtype)


def create(spec) -> Initializer:
    if isinstance(spec, Initializer) or callable(spec) and not isinstance(spec, str):
        return spec
    if spec is None:
        return Uniform()
    return registry.get(spec)()
