"""INT8 post-training quantization driver — capability parity with
``python/mxnet/contrib/quantization.py`` (quantize_model:405, calibration
:109-194) re-designed for the Gluon/jit path.

Where the reference rewrites the *symbol graph* (quantize_graph_pass.cc) into
quantize→quantized_op→requantize chains and feeds a calibration table to
``MXSetCalibTableToQuantizedSymbol``, here ``quantize_net`` rewrites the *block
tree*: every eligible ``Conv2D``/``Dense`` child is swapped for a quantized
twin that keeps int8 weights (per-output-channel scales) and quantizes its
input with a calibrated scale, computing on the MXU's int8 path
(ops/quantization.py). Calibration modes match the reference:

* ``none``    — dynamic: input ranges computed on the fly inside the compiled
                graph (a data-dependent max, free under XLA fusion).
* ``naive``   — min/max over the calibration batches (quantization.py:109
                ``_collect_layer_statistics`` naive mode).
* ``entropy`` — KL-divergence-optimal thresholds from activation histograms
                (quantization.py:147 ``_get_optimal_thresholds``,
                the TensorRT-style algorithm).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray
from ..ops.quantization import (int8_conv, int8_dense, quantize_weight,
                                zero_point_corr_conv, zero_point_corr_dense)

__all__ = ["quantize_net", "QuantizedConv2D", "QuantizedDense",
           "_get_optimal_threshold"]


# ---------------------------------------------------------------------------
# quantized layer twins
# ---------------------------------------------------------------------------


class _QuantizedLayer(HybridBlock):
    """Shared plumbing: holds int8 weight + scales; input scale is either a
    calibrated constant or computed dynamically per batch."""

    def __init__(self, w_q, w_scale, bias, act, input_absmax, unsigned=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._w_q = w_q
        self._w_scale = w_scale
        self._bias = bias
        self._act = act
        self._input_absmax = input_absmax  # None => dynamic; max(x) if unsigned
        self._unsigned = unsigned          # uint8 activation range [0, max]

    def _x_scale(self, x):
        if self._unsigned:
            # unsigned range is [0, max(x)] — NOT max|x|, which would waste
            # resolution whenever |min| > max (negatives clamp regardless)
            if self._input_absmax is not None:
                return jnp.float32(255.0 / max(self._input_absmax, 1e-30))
            return 255.0 / jnp.maximum(jnp.max(x), 1e-30)
        if self._input_absmax is not None:
            return jnp.float32(127.0 / max(self._input_absmax, 1e-30))
        return 127.0 / jnp.maximum(jnp.max(jnp.abs(x)), 1e-30)

    def _finish(self, out):
        if self._act:
            from .. import ndarray as nd
            return nd.Activation(NDArray(out), act_type=self._act)
        return NDArray(out)


class QuantizedDense(_QuantizedLayer):
    """int8 twin of ``nn.Dense`` (quantized_fully_connected.cc parity)."""

    def __init__(self, dense: nn.Dense, input_absmax=None, unsigned=False,
                 **kwargs):
        w = dense.weight.data().data
        w_q, w_scale = quantize_weight(w, per_channel_axis=0)
        bias = dense.bias.data().data if dense._use_bias else None
        super().__init__(w_q, w_scale, bias, dense._act, input_absmax,
                         unsigned, **kwargs)
        self._flatten = dense._flatten
        # zero-point correction is a per-layer constant — pay it once here,
        # not per forward (matters in eager mode)
        self._zp_corr = zero_point_corr_dense(w_q) if unsigned else None

    def forward(self, x):
        raw = x.data if isinstance(x, NDArray) else x
        if self._flatten and raw.ndim > 2:
            raw = raw.reshape(raw.shape[0], -1)
        out = int8_dense(raw, self._w_q, self._w_scale, self._x_scale(raw),
                         self._bias, x_unsigned=self._unsigned,
                         zp_corr=self._zp_corr)
        return self._finish(out)


class QuantizedConv2D(_QuantizedLayer):
    """int8 twin of ``nn.Conv2D`` (quantized_conv.cc parity)."""

    def __init__(self, conv, input_absmax=None, unsigned=False, **kwargs):
        w = conv.weight.data().data
        w_q, w_scale = quantize_weight(w, per_channel_axis=0)
        bias = conv.bias.data().data if conv._use_bias else None
        super().__init__(w_q, w_scale, bias, conv._act, input_absmax,
                         unsigned, **kwargs)
        self._stride = conv._strides
        self._pad = conv._padding
        self._dilate = conv._dilation
        self._groups = conv._groups
        # input shape -> 128·conv(1,w); bounded LRU so variable-shape
        # inference (batch/resolution sweeps) can't grow device residency
        # without limit
        from collections import OrderedDict
        self._corr_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._corr_cache_cap = 8

    def _zp_corr(self, shape):
        if not self._unsigned:
            return None
        got = self._corr_cache.get(shape)
        if got is None:
            got = zero_point_corr_conv(shape, self._w_q, self._stride,
                                       self._pad, self._dilate, self._groups)
            self._corr_cache[shape] = got
            if len(self._corr_cache) > self._corr_cache_cap:
                self._corr_cache.popitem(last=False)
        else:
            self._corr_cache.move_to_end(shape)
        return got

    def forward(self, x):
        raw = x.data if isinstance(x, NDArray) else x
        out = int8_conv(raw, self._w_q, self._w_scale, self._x_scale(raw),
                        self._bias, self._stride, self._pad, self._dilate,
                        self._groups, x_unsigned=self._unsigned,
                        zp_corr=self._zp_corr(raw.shape))
        return self._finish(out)


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------


# The calibration math (smoothed-KL threshold sweep) moved to
# ``mxtpu.quant.calibrate`` as a STREAMING API; re-exported here because both
# are long-standing public-ish surface (``_get_optimal_threshold`` is in
# ``__all__`` and pinned by tests).
from ..quant.calibrate import (_get_optimal_threshold,  # noqa: E402,F401
                               _smooth_distribution, collect_stats)


def _eligible(block) -> bool:
    return isinstance(block, (nn.Dense, nn.Conv2D))


def _walk(block, prefix="") -> List[Tuple[HybridBlock, str, HybridBlock]]:
    """Yield (parent, child_key, child) for every eligible layer."""
    out = []
    for key, child in block._children.items():
        name = f"{prefix}{key}"
        if _eligible(child):
            out.append((block, key, child, name))
        else:
            out.extend(_walk(child, name + "."))
    return out


def _collect_input_stats(net, sites, calib_data, num_calib_batches, mode,
                         logger):
    """Run calibration batches with pre-hooks folding each site's input into
    a :class:`~mxtpu.quant.calibrate.StreamingCalibrator` (constant memory —
    the old path concatenated every activation on the host)."""
    calib = collect_stats(net, sites, calib_data, num_calib_batches)
    absmax: Dict[str, Optional[float]] = {}
    minval: Dict[str, Optional[float]] = {}
    maxval: Dict[str, Optional[float]] = {}
    for *_, name in sites:
        if not calib.seen(name):
            absmax[name] = minval[name] = maxval[name] = None
            continue
        minval[name], maxval[name] = calib.minmax(name)
        absmax[name] = (calib.absmax(name) if mode == "naive"
                        else calib.threshold(name))
        if logger:
            logger.info("calib %s: absmax=%.5g min=%.5g max=%.5g (%s)", name,
                        absmax[name], minval[name], maxval[name], mode)
    return absmax, minval, maxval


def quantize_net(net, quantized_dtype: str = "int8",
                 exclude: Sequence[str] = (), calib_mode: str = "none",
                 calib_data=None, num_calib_batches: Optional[int] = None,
                 logger: Optional[logging.Logger] = None):
    """Quantize a (initialized, already-shaped) gluon net in place and return it.

    Parity: ``contrib.quantization.quantize_model`` (quantization.py:405) /
    ``quantize_net`` of later reference lines. ``exclude`` filters by substring
    of the layer's path (reference ``excluded_sym_names``). The first and last
    layers are commonly excluded by callers for accuracy.
    """
    if quantized_dtype not in ("int8", "uint8", "auto"):
        raise ValueError(f"quantized_dtype {quantized_dtype!r} (int8 | uint8 "
                         f"| auto)")
    if calib_mode not in ("none", "naive", "entropy"):
        raise ValueError(f"calib_mode {calib_mode!r}")
    sites = [(p, k, c, n) for p, k, c, n in _walk(net)
             if not any(e in n for e in exclude)]
    for p, k, c, n in sites:
        if c.weight._data is None:
            raise ValueError(f"layer {n} has uninitialized weight; run a "
                             "forward pass before quantize_net")
    if quantized_dtype == "auto" and calib_mode == "none":
        raise ValueError(
            "quantized_dtype='auto' needs calibration to decide signedness "
            "per tensor — pass calib_mode='naive'/'entropy' with calib_data, "
            "or choose 'int8'/'uint8' explicitly")
    absmax: Dict[str, Optional[float]] = {n: None for *_, n in sites}
    minval: Dict[str, Optional[float]] = dict(absmax)
    maxval: Dict[str, Optional[float]] = dict(absmax)
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise ValueError(f"calib_mode={calib_mode!r} requires calib_data")
        absmax, minval, maxval = _collect_input_stats(
            net, sites, calib_data, num_calib_batches, calib_mode, logger)
    for parent, key, child, name in sites:
        # signedness per tensor (reference quantize_graph_pass 'auto': uint8
        # where the calibrated activation is non-negative — post-ReLU layers —
        # int8 elsewhere). Explicit 'uint8' forces the unsigned range (values
        # below 0 clamp, as in the reference's uint8 kernels).
        if quantized_dtype == "uint8":
            unsigned = True
        elif quantized_dtype == "auto":
            unsigned = minval[name] is not None and minval[name] >= 0.0
        else:
            unsigned = False
        if logger and unsigned:
            logger.info("layer %s: uint8 activation range", name)
        # unsigned layers calibrate over [0, max]; signed over ±absmax
        rng = maxval[name] if unsigned else absmax[name]
        if isinstance(child, nn.Dense):
            q = QuantizedDense(child, rng, unsigned)
        else:
            q = QuantizedConv2D(child, rng, unsigned)
        parent._children[key] = q
        for attr, val in list(parent.__dict__.items()):
            if val is child:
                object.__setattr__(parent, attr, q)
    return net
