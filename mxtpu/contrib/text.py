"""Text utilities — capability parity with ``python/mxnet/contrib/text``
(vocab.py Vocabulary, embedding.py token embeddings, utils.py counters).

Zero-egress deviation: the reference downloads pretrained GloVe/FastText
archives; here every embedding loads from a LOCAL file (same text format:
``token<delim>v1<delim>v2...`` per line). ``GloVe``/``FastText`` classes exist
for API parity and accept ``pretrained_file_path=`` pointing at a local mirror.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = ["count_tokens_from_str", "Vocabulary", "CustomEmbedding", "GloVe",
           "FastText", "CompositeEmbedding"]


def count_tokens_from_str(source_str: str, token_delim: str = " ",
                          seq_delim: str = "\n", to_lower: bool = False,
                          counter_to_update: Optional[collections.Counter] = None
                          ) -> collections.Counter:
    """utils.py:28 parity: token frequency counter from raw text."""
    if to_lower:
        source_str = source_str.lower()
    tokens = source_str.replace(seq_delim, token_delim).split(token_delim)
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(t for t in tokens if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with an unknown token and reserved tokens
    (vocab.py:30 parity). Index 0 is the unknown token; reserved tokens
    follow; remaining tokens are frequency-sorted (ties broken
    alphabetically), filtered by ``min_freq``/``most_freq_count``."""

    def __init__(self, counter: Optional[collections.Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            rset = set(reserved_tokens)
            if len(rset) != len(reserved_tokens) or unknown_token in rset:
                raise ValueError("reserved tokens must be unique and must not "
                                 "contain the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = list(reserved_tokens) if reserved_tokens else None
        self._idx_to_token: List[str] = [unknown_token] + \
            (list(reserved_tokens) if reserved_tokens else [])
        self._token_to_idx: Dict[str, int] = {
            t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        taken = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and taken >= most_freq_count:
                break
            if token in self._token_to_idx:
                continue
            self._token_to_idx[token] = len(self._idx_to_token)
            self._idx_to_token.append(token)
            taken += 1

    def __len__(self) -> int:
        return len(self._idx_to_token)

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens: Union[str, Sequence[str]]):
        """Token(s) → index/indices; unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices: Union[int, Sequence[int]]):
        single = isinstance(indices, int)
        idxs = [indices] if single else list(indices)
        for i in idxs:
            if not 0 <= i < len(self):
                raise ValueError(f"index {i} out of vocabulary range")
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


class _TokenEmbedding(Vocabulary):
    """Base embedding: maps every vocabulary token to a vector
    (embedding.py:132 parity; file format ``token v1 v2 ...``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec: Optional[NDArray] = None

    def _load_embedding(self, path: str, elem_delim: str = " ",
                        init_unknown_vec: Callable = np.zeros,
                        encoding: str = "utf8"):
        vecs: Dict[str, np.ndarray] = {}
        with open(path, encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2 and \
                        all(p.isdigit() for p in parts):
                    continue  # fastText "count dim" header
                if len(parts) < 2:
                    continue  # malformed/blank line
                token, elems = parts[0], parts[1:]
                if self._vec_len == 0:
                    self._vec_len = len(elems)
                if len(elems) != self._vec_len:
                    continue  # skip lines with inconsistent width
                if token and token not in vecs:
                    vecs[token] = np.asarray(elems, np.float32)
        for token in vecs:
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
        table = np.zeros((len(self), self._vec_len), np.float32)
        table[0] = init_unknown_vec(self._vec_len)
        for token, v in vecs.items():
            table[self._token_to_idx[token]] = v
        self._set_table(table)

    def _set_table(self, table: np.ndarray):
        """Single mutation point: keeps a host-side copy so lookups never
        read the device table back (multi-GB for real embedding mirrors)."""
        self._table_np = np.asarray(table, np.float32)
        self._idx_to_vec = nd.array(self._table_np)

    @property
    def vec_len(self) -> int:
        return self._vec_len

    @property
    def idx_to_vec(self) -> NDArray:
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup: bool = False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else list(tokens)
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t, 0)
            if i == 0 and lower_case_backup:
                i = self._token_to_idx.get(t.lower(), 0)
            idxs.append(i)
        out = self._table_np[np.asarray(idxs)]
        return nd.array(out[0] if single else out)

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else list(tokens)
        vecs = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors)
        vecs = vecs.reshape(len(toks), self._vec_len)
        table = np.array(self._table_np)
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is unknown; only existing "
                                 "tokens can be updated")
            table[self._token_to_idx[t]] = v
        self._set_table(table)

    def _build_for_vocabulary(self, vocabulary: Vocabulary, source):
        """Restrict to a vocabulary's tokens — carries the vocabulary's
        unknown/reserved metadata (embedding.py:304-311 semantics). Safe to
        call with ``source is self``: the source table is snapshotted first."""
        table = np.zeros((len(vocabulary), source._vec_len), np.float32)
        src = source._idx_to_vec.asnumpy()
        src_tok = dict(source._token_to_idx)
        for i, t in enumerate(vocabulary.idx_to_token):
            table[i] = src[src_tok.get(t, 0)]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens
        self._vec_len = source._vec_len
        self._set_table(table)


class CustomEmbedding(_TokenEmbedding):
    """Embedding from a local text file (embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path: str, elem_delim: str = " ",
                 encoding: str = "utf8", init_unknown_vec: Callable = np.zeros,
                 vocabulary: Optional[Vocabulary] = None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        if vocabulary is not None:
            self._build_for_vocabulary(vocabulary, self)


class GloVe(CustomEmbedding):
    """GloVe-format embedding (embedding.py:468). Zero-egress: pass
    ``pretrained_file_path`` to a local ``glove.*.txt`` mirror."""

    def __init__(self, pretrained_file_path: Optional[str] = None, **kwargs):
        if pretrained_file_path is None:
            raise NotImplementedError(
                "this environment has no network egress: download glove.*.txt "
                "yourself and pass pretrained_file_path=")
        super().__init__(pretrained_file_path, **kwargs)


class FastText(CustomEmbedding):
    """FastText .vec embedding (embedding.py:558); header line is skipped."""

    def __init__(self, pretrained_file_path: Optional[str] = None, **kwargs):
        if pretrained_file_path is None:
            raise NotImplementedError(
                "this environment has no network egress: download wiki.*.vec "
                "yourself and pass pretrained_file_path=")
        super().__init__(pretrained_file_path, **kwargs)


class _FromTable:
    """Adapter: a (vocab-aligned) table masquerading as an embedding source."""

    def __init__(self, table, vocabulary):
        self._vec_len = table.shape[1]
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_vec = nd.array(table)
        self._table_np = np.asarray(table, np.float32)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary: Vocabulary,
                 token_embeddings: Sequence[_TokenEmbedding]):
        super().__init__()
        parts = []
        for e in token_embeddings:
            piece = _TokenEmbedding()
            piece._build_for_vocabulary(vocabulary, e)
            parts.append(piece._idx_to_vec.asnumpy())
        self._build_for_vocabulary(vocabulary, _FromTable(
            np.concatenate(parts, axis=1), vocabulary))
