"""TensorBoard scalar logging — ``python/mxnet/contrib/tensorboard.py`` parity.

The reference's ``LogMetricsCallback`` forwards metrics to an external
``tensorboard`` package. This implementation has no dependency: it writes the
TensorBoard on-disk format directly — TFRecord-framed protobuf ``Event``
messages with masked CRC32C checksums — so standard TensorBoard can point at
the logdir. Only the scalar summary family is encoded (the reference callback
logs exactly that).

Wire format (stable, documented by the TF event-file readers):
  record  = uint64 len | crc32c_masked(len) | bytes | crc32c_masked(bytes)
  Event   = 1: wall_time (double), 2: step (int64),
            3: file_version (string, first record only), 5: Summary
  Summary = repeated 1: Value;  Value = 1: tag (string), 2: simple_value (float)
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

__all__ = ["SummaryWriter", "LogMetricsCallback"]

# ---- CRC32C (Castagnoli), table-driven ------------------------------------
def _build_crc_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_CRC_TABLE = _build_crc_table()  # built at import: immutable and thread-safe


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---- minimal protobuf writers ---------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _field_double(num: int, v: float) -> bytes:
    return _varint((num << 3) | 1) + struct.pack("<d", v)


def _field_float(num: int, v: float) -> bytes:
    return _varint((num << 3) | 5) + struct.pack("<f", v)


def _field_varint(num: int, v: int) -> bytes:
    return _varint(num << 3) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _event(wall_time: float, step: int = 0, file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    out = _field_double(1, wall_time)
    if step:
        out += _field_varint(2, step)
    if file_version is not None:
        out += _field_bytes(3, file_version.encode())
    if summary is not None:
        out += _field_bytes(5, summary)
    return out


def _scalar_summary(tag: str, value: float) -> bytes:
    v = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, v)


class SummaryWriter:
    """Write scalar events TensorBoard can read; no tensorboard dependency."""

    _seq = 0

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        # pid + in-process counter uniquify concurrent writers on one logdir
        SummaryWriter._seq += 1
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}.{SummaryWriter._seq}")
        self.path = os.path.join(logdir, fname)
        self._f = open(self.path, "wb")
        self._write(_event(time.time(), file_version="brain.Event:2"))

    def _write(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header + struct.pack("<I", _masked_crc(header)) +
                      payload + struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, global_step: int = 0) -> None:
        self._write(_event(time.time(), global_step,
                           summary=_scalar_summary(tag, value)))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LogMetricsCallback:
    """Batch-end callback logging every metric to TensorBoard
    (contrib/tensorboard.py LogMetricsCallback parity)."""

    def __init__(self, logging_dir: str, prefix: Optional[str] = None):
        self.prefix = prefix
        self._writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param) -> None:
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            tag = f"{self.prefix}-{name}" if self.prefix else name
            self._writer.add_scalar(tag, value, self._step)
        self._writer.flush()
