"""ONNX interchange — ``mx.contrib.onnx`` surface (reference
python/mxnet/contrib/onnx): ``import_model`` consumes real ONNX files
(onnx2mx) and ``export_model`` produces them (mx2onnx), both through the
dependency-free wire codec in ``_proto.py``. StableHLO
(``mxtpu.jit.export_stablehlo``) remains the compiler-native portable form."""

from .mx2onnx import export_model
from .onnx2mx import get_model_metadata, import_graph, import_model
