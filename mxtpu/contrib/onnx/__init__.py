"""ONNX import — ``mx.contrib.onnx.import_model`` surface (reference
python/mxnet/contrib/onnx). Export's portable-graph role is covered by
StableHLO (``mxtpu.jit.export_stablehlo``); import speaks real ONNX so zoo
artifacts cross over."""

from .onnx2mx import get_model_metadata, import_graph, import_model
