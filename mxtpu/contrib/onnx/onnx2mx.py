"""ONNX graph → Symbol + params — functional counterpart of the reference's
``contrib.onnx.onnx2mx`` (python/mxnet/contrib/onnx/onnx2mx/import_model.py:84
``import_model``, op tables in ``_op_translations.py``).

Design differences from the reference: the reference shells out to the
``onnx`` package and mutates attr dicts through a convention table; here the
protobuf is parsed directly (``_proto.py`` — no onnx dependency in the image)
and each op translates through one small function building on the same
``mx.sym`` wrappers a user would call, so an imported graph is
indistinguishable from a hand-composed one (binds, infers, executes, and
re-serializes like any Symbol).

Covered op set: the model-zoo families the round-4 verdict names
(conv/BN/relu/pool/gemm/concat/softmax/flatten/add) plus the ops torch's
exporter emits around them (MatMul, Clip, GlobalAveragePool, Reshape,
Transpose, Dropout/Identity passthrough, Constant, elementwise arithmetic,
Sigmoid/Tanh, Squeeze/Unsqueeze, Pad).
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

import numpy as np

from ... import symbol as sym
from ...ndarray.ndarray import NDArray
from ._proto import Graph, parse_model

__all__ = ["import_model", "import_graph", "get_model_metadata"]


def _san(name: str) -> str:
    """ONNX tensor names may be arbitrary strings; Symbol variable names feed
    python identifiers downstream."""
    s = re.sub(r"[^0-9a-zA-Z_]", "_", name)
    return s if s and not s[0].isdigit() else "_" + s


def _pads(attrs, node) -> Tuple[int, ...]:
    pads = attrs.get("pads", ())
    if not pads:
        return ()
    n = len(pads) // 2
    begin, end = tuple(pads[:n]), tuple(pads[n:])
    if begin != end:
        raise NotImplementedError(
            f"asymmetric ONNX pads {pads} on {node.op_type} {node.name!r}: "
            "prepend an explicit Pad node (the reference importer has the "
            "same symmetric restriction, _op_translations.py)")
    return begin


class _Importer:
    def __init__(self, graph: Graph, opset: int):
        self.g = graph
        self.opset = opset
        self.tensors: Dict[str, sym.Symbol] = {}
        self.arg_params: Dict[str, NDArray] = {}
        self.aux_params: Dict[str, NDArray] = {}
        self.data_names: List[str] = []

    # -- tensor helpers ----------------------------------------------------
    def _const_value(self, name: str) -> np.ndarray:
        """An initializer consumed as a STRUCTURAL value (Reshape shape,
        Clip bounds...)."""
        if name in self.g.initializers:
            return self.g.initializers[name]
        raise NotImplementedError(
            f"dynamic (non-initializer) structural input {name!r}")

    def _param(self, name: str, aux: bool = False) -> sym.Symbol:
        """Materialize an initializer as a Variable + param entry."""
        key = _san(name)
        if key not in self.tensors:
            self.tensors[key] = sym.Variable(key)
            store = self.aux_params if aux else self.arg_params
            store[key] = NDArray(np.ascontiguousarray(
                self.g.initializers[name]))
        return self.tensors[key]

    def _in(self, node, i, aux: bool = False):
        name = node.inputs[i]
        if name == "":
            return None
        if name in self.g.initializers and _san(name) not in self.tensors:
            return self._param(name, aux=aux)
        return self.tensors[_san(name)]

    def _set(self, node, out):
        outs = out if isinstance(out, (list, tuple)) else [out]
        for name, s in zip(node.outputs, outs):
            self.tensors[_san(name)] = s

    # -- op translations ---------------------------------------------------
    def op_Conv(self, n):
        w = self.g.initializers[n.inputs[1]]
        attrs = n.attrs
        kwargs = dict(kernel=tuple(attrs.get("kernel_shape", w.shape[2:])),
                      num_filter=int(w.shape[0]),
                      num_group=int(attrs.get("group", 1)))
        if attrs.get("strides"):
            kwargs["stride"] = tuple(attrs["strides"])
        if attrs.get("dilations"):
            kwargs["dilate"] = tuple(attrs["dilations"])
        p = _pads(attrs, n)
        if p:
            kwargs["pad"] = p
        data, weight = self._in(n, 0), self._in(n, 1)
        bias = self._in(n, 2) if len(n.inputs) > 2 else None
        if bias is None:
            kwargs["no_bias"] = True
            return sym.Convolution(data, weight, name=_san(n.outputs[0]),
                                   **kwargs)
        return sym.Convolution(data, weight, bias, name=_san(n.outputs[0]),
                               **kwargs)

    def op_BatchNormalization(self, n):
        return sym.BatchNorm(
            self._in(n, 0), self._in(n, 1), self._in(n, 2),
            self._in(n, 3, aux=True), self._in(n, 4, aux=True),
            eps=float(n.attrs.get("epsilon", 1e-5)),
            momentum=float(n.attrs.get("momentum", 0.9)),
            fix_gamma=False, use_global_stats=True,
            name=_san(n.outputs[0]))

    def _act(self, n, act_type):
        return sym.Activation(self._in(n, 0), act_type=act_type,
                              name=_san(n.outputs[0]))

    def op_Relu(self, n):
        return self._act(n, "relu")

    def op_Sigmoid(self, n):
        return self._act(n, "sigmoid")

    def op_Tanh(self, n):
        return self._act(n, "tanh")

    def _pool(self, n, pool_type, global_pool=False):
        kwargs = dict(pool_type=pool_type, global_pool=global_pool)
        if not global_pool:
            kwargs["kernel"] = tuple(n.attrs["kernel_shape"])
            if n.attrs.get("strides"):
                kwargs["stride"] = tuple(n.attrs["strides"])
            p = _pads(n.attrs, n)
            if p:
                kwargs["pad"] = p
            if pool_type == "avg":
                kwargs["count_include_pad"] = bool(
                    n.attrs.get("count_include_pad", 0))
            if n.attrs.get("ceil_mode"):
                kwargs["pooling_convention"] = "full"
        else:
            kwargs["kernel"] = (1, 1)
        return sym.Pooling(self._in(n, 0), name=_san(n.outputs[0]), **kwargs)

    def op_MaxPool(self, n):
        return self._pool(n, "max")

    def op_AveragePool(self, n):
        return self._pool(n, "avg")

    def op_GlobalAveragePool(self, n):
        return self._pool(n, "avg", global_pool=True)

    def op_GlobalMaxPool(self, n):
        return self._pool(n, "max", global_pool=True)

    def op_Gemm(self, n):
        if n.attrs.get("alpha", 1.0) != 1.0 or n.attrs.get("beta", 1.0) != 1.0:
            raise NotImplementedError("Gemm with alpha/beta != 1")
        if n.attrs.get("transA", 0):
            raise NotImplementedError("Gemm transA")
        wname = n.inputs[1]
        w = self.g.initializers[wname]
        if not n.attrs.get("transB", 0):
            # FullyConnected wants (num_hidden, in); fold the transpose into
            # a RENAMED parameter — mutating the shared initializer would
            # corrupt other consumers of the same tensor (tied weights)
            tname = wname + "__fc_T"
            if tname not in self.g.initializers:
                self.g.initializers[tname] = np.ascontiguousarray(w.T)
            wname, w = tname, self.g.initializers[tname]
        num_hidden = int(w.shape[0])
        data, weight = self._in(n, 0), self._param(wname)
        if len(n.inputs) > 2:
            return sym.FullyConnected(data, weight, self._in(n, 2),
                                      num_hidden=num_hidden, flatten=False,
                                      name=_san(n.outputs[0]))
        return sym.FullyConnected(data, weight, num_hidden=num_hidden,
                                  no_bias=True, flatten=False,
                                  name=_san(n.outputs[0]))

    def op_MatMul(self, n):
        return sym.dot(self._in(n, 0), self._in(n, 1),
                       name=_san(n.outputs[0]))

    def _broadcast(self, n, opname):
        return getattr(sym, opname)(self._in(n, 0), self._in(n, 1),
                                    name=_san(n.outputs[0]))

    def op_Add(self, n):
        return self._broadcast(n, "broadcast_add")

    def op_Sub(self, n):
        return self._broadcast(n, "broadcast_sub")

    def op_Mul(self, n):
        return self._broadcast(n, "broadcast_mul")

    def op_Div(self, n):
        return self._broadcast(n, "broadcast_div")

    def op_Concat(self, n):
        ins = [self._in(n, i) for i in range(len(n.inputs))]
        return sym.concat(*ins, dim=int(n.attrs.get("axis", 1)),
                          name=_san(n.outputs[0]))

    def op_Softmax(self, n):
        data = self._in(n, 0)
        if self.opset >= 13:
            return sym.softmax(data, axis=int(n.attrs.get("axis", -1)),
                               name=_san(n.outputs[0]))
        # opset < 13 semantics: COALESCE dims from `axis` onward into one 2-D
        # softmax (the rank-2 case degenerates to a plain axis softmax)
        axis = int(n.attrs.get("axis", 1))
        flat = sym.reshape(data, shape=(0,) * axis + (-1,))
        soft = sym.softmax(flat, axis=-1)
        return sym.reshape_like(soft, data, name=_san(n.outputs[0]))

    def op_Flatten(self, n):
        if int(n.attrs.get("axis", 1)) != 1:
            raise NotImplementedError("Flatten axis != 1")
        return sym.flatten(self._in(n, 0), name=_san(n.outputs[0]))

    def op_Reshape(self, n):
        shape = tuple(int(d) for d in self._const_value(n.inputs[1]))
        return sym.reshape(self._in(n, 0), shape=shape,
                           name=_san(n.outputs[0]))

    def op_Transpose(self, n):
        return sym.transpose(self._in(n, 0),
                             axes=tuple(n.attrs.get("perm", ())),
                             name=_san(n.outputs[0]))

    def op_Clip(self, n):
        lo = n.attrs.get("min")
        hi = n.attrs.get("max")
        if lo is None and len(n.inputs) > 1 and n.inputs[1]:
            lo = float(self._const_value(n.inputs[1]))
        if hi is None and len(n.inputs) > 2 and n.inputs[2]:
            hi = float(self._const_value(n.inputs[2]))
        return sym.clip(self._in(n, 0),
                        a_min=float(lo if lo is not None else -np.inf),
                        a_max=float(hi if hi is not None else np.inf),
                        name=_san(n.outputs[0]))

    def op_Dropout(self, n):
        return self._in(n, 0)          # inference import: identity

    def op_Identity(self, n):
        return self._in(n, 0)

    def op_Squeeze(self, n):
        axes = n.attrs.get("axes")
        if axes is None and len(n.inputs) > 1:
            axes = [int(a) for a in self._const_value(n.inputs[1])]
        return sym.squeeze(self._in(n, 0), axis=tuple(axes) if axes else None,
                           name=_san(n.outputs[0]))

    def op_Unsqueeze(self, n):
        axes = n.attrs.get("axes")
        if axes is None and len(n.inputs) > 1:
            axes = [int(a) for a in self._const_value(n.inputs[1])]
        out = self._in(n, 0)
        for ax in sorted(int(a) for a in axes):
            out = sym.expand_dims(out, axis=ax)
        return out

    def op_Pad(self, n):
        pads = n.attrs.get("pads")
        if pads is None:
            pads = [int(p) for p in self._const_value(n.inputs[1])]
        value = n.attrs.get("value")                 # opset < 11: attr
        if value is None and len(n.inputs) > 2 and n.inputs[2]:
            value = float(self._const_value(n.inputs[2]))   # opset >= 11
        nd_ = len(pads) // 2
        pw = []
        for i in range(nd_):
            pw += [int(pads[i]), int(pads[i + nd_])]
        return sym.pad(self._in(n, 0), mode=n.attrs.get("mode", "constant"),
                       pad_width=tuple(pw),
                       constant_value=float(value if value is not None
                                            else 0.0),
                       name=_san(n.outputs[0]))

    # -- constant folding --------------------------------------------------
    # torch's exporter builds Pad/Reshape operands through small shape
    # subgraphs (ConstantOfShape/Concat/Slice/Cast over int tensors); when
    # every input is a known constant, evaluate with numpy instead of
    # translating (the reference importer's _op_translations do the same via
    # attribute conversion)
    _FOLDABLE = {"Constant", "ConstantOfShape", "Concat", "Slice", "Cast",
                 "Reshape", "Transpose", "Unsqueeze", "Squeeze", "Gather",
                 "Add", "Sub", "Mul", "Div", "Neg"}

    _CAST_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
                    7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}

    def _try_fold(self, n) -> bool:
        if n.op_type not in self._FOLDABLE:
            return False
        if n.op_type != "Constant" and not all(
                i in self.g.initializers for i in n.inputs if i):
            return False
        ins = [self.g.initializers[i] for i in n.inputs if i]
        a = n.attrs
        t = n.op_type
        if t == "Constant":
            out = a["value"].array
        elif t == "ConstantOfShape":
            fill = a["value"].array if "value" in a else np.zeros(1, np.float32)
            out = np.full([int(d) for d in ins[0]], fill.ravel()[0],
                          fill.dtype)
        elif t == "Concat":
            out = np.concatenate(ins, axis=int(a.get("axis", 0)))
        elif t == "Slice":
            starts = a.get("starts") or [int(v) for v in ins[1]]
            ends = a.get("ends") or [int(v) for v in ins[2]]
            axes = (a.get("axes") or
                    ([int(v) for v in ins[3]] if len(ins) > 3
                     else list(range(len(starts)))))
            steps = ([int(v) for v in ins[4]] if len(ins) > 4
                     else [1] * len(starts))
            sl = [slice(None)] * ins[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[ax] = slice(s, e, st)
            out = ins[0][tuple(sl)]
        elif t == "Cast":
            out = ins[0].astype(self._CAST_DTYPES[int(a["to"])])
        elif t == "Reshape":
            out = ins[0].reshape([int(d) for d in ins[1]])
        elif t == "Transpose":
            out = np.transpose(ins[0], a.get("perm"))
        elif t == "Unsqueeze":
            axes = a.get("axes") or [int(v) for v in ins[1]]
            out = ins[0]
            for ax in sorted(int(x) for x in axes):
                out = np.expand_dims(out, ax)
        elif t == "Squeeze":
            axes = a.get("axes") or ([int(v) for v in ins[1]]
                                     if len(ins) > 1 else None)
            out = np.squeeze(ins[0], tuple(axes) if axes else None)
        elif t == "Gather":
            out = np.take(ins[0], ins[1], axis=int(a.get("axis", 0)))
        elif t == "Neg":
            out = -ins[0]
        else:                                       # Add/Sub/Mul/Div
            op = {"Add": np.add, "Sub": np.subtract,
                  "Mul": np.multiply, "Div": np.divide}[t]
            out = op(ins[0], ins[1])
        self.g.initializers[n.outputs[0]] = np.asarray(out)
        return True

    # -- driver ------------------------------------------------------------
    def run(self):
        for name, shape in self.g.inputs:
            if name in self.g.initializers:
                continue                             # params appear lazily
            key = _san(name)
            self.tensors[key] = sym.Variable(key)
            self.data_names.append(key)
        for n in self.g.nodes:
            if self._try_fold(n):
                continue
            fn = getattr(self, f"op_{n.op_type}", None)
            if fn is None:
                raise NotImplementedError(
                    f"ONNX op {n.op_type!r} (node {n.name!r}) has no "
                    f"translation — covered set: "
                    f"{sorted(a[3:] for a in dir(self) if a.startswith('op_'))}")
            self._set(n, fn(n))
        outs = [self.tensors[_san(o)] for o in self.g.outputs]
        s = outs[0] if len(outs) == 1 else sym.Group(outs)
        return s, self.arg_params, self.aux_params


def import_graph(model_bytes: bytes):
    graph, opset = parse_model(model_bytes)
    return _Importer(graph, opset).run()


def import_model(model_file: str):
    """(sym, arg_params, aux_params) from an ONNX file — reference
    ``import_model`` API (onnx2mx/import_model.py:84)."""
    with open(model_file, "rb") as f:
        return import_graph(f.read())


def get_model_metadata(model_file: str):
    """Input/output tensor names + shapes (reference get_model_metadata)."""
    with open(model_file, "rb") as f:
        graph, _ = parse_model(f.read())
    ins = [( _san(n), s) for n, s in graph.inputs
           if n not in graph.initializers]
    return {"input_tensor_data": ins,
            "output_tensor_data": [(_san(o), None) for o in graph.outputs]}
