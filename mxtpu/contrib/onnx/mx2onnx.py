"""Symbol → ONNX export — functional counterpart of the reference's
``contrib.onnx.mx2onnx`` (python/mxnet/contrib/onnx/mx2onnx/
export_model.py:95 ``export_model``, op tables in ``_op_translations.py``).

The graph walks the Symbol DAG directly (no executor bind needed) and the
protobuf is emitted by the wire writer in ``_proto.py`` — no onnx package in
the image. Covered op set mirrors the importer: the zoo families
(conv/BN/activations/pools/FC/concat/softmax/flatten/elementwise) plus
reshape/transpose/clip/dropout.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import _proto as P

__all__ = ["export_model"]

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus"}
_ELEMWISE = {"elemwise_add": "Add", "broadcast_add": "Add", "_plus": "Add",
             "elemwise_sub": "Sub", "broadcast_sub": "Sub", "_minus": "Sub",
             "elemwise_mul": "Mul", "broadcast_mul": "Mul", "_mul": "Mul",
             "elemwise_div": "Div", "broadcast_div": "Div", "_div": "Div"}


class _Exporter:
    def __init__(self, sym, params: Dict, input_shapes: Dict):
        self.sym = sym
        self.params = params
        self.input_shapes = dict(input_shapes)
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.inputs: List[bytes] = []
        self._emitted_inits = set()

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _tname(node, j: int) -> str:
        return node.name if j == 0 else f"{node.name}_out{j}"

    def _in_names(self, node) -> List[str]:
        return [self._tname(c, j) for c, j in node.inputs]

    def _add_init(self, name: str, arr) -> str:
        if name not in self._emitted_inits:
            raw = np.asarray(arr.asnumpy() if hasattr(arr, "asnumpy") else arr)
            if raw.dtype.name == "bfloat16":
                raw = raw.astype(np.float32)
            self.initializers.append(P.w_tensor(name, raw))
            self._emitted_inits.add(name)
        return name

    def _emit(self, op: str, ins, outs, name: str, attrs=None):
        self.nodes.append(P.w_node(op, ins, outs, name=name, attrs=attrs))

    # -- per-op translation -------------------------------------------------
    def _convert(self, node):
        key = node.op_key
        name = node.name
        ins = self._in_names(node)
        out = self._tname(node, 0)
        a = node.attrs

        if key in ("FullyConnected",):
            srcs = list(ins)
            if a.get("flatten", True):     # identity on 2-D, required on >2-D
                flat = f"{name}_flat"
                self._emit("Flatten", [srcs[0]], [flat], f"{name}_flatten",
                           {"axis": 1})
                srcs[0] = flat
            self._emit("Gemm", srcs, [out], name,
                       {"alpha": 1.0, "beta": 1.0, "transB": 1})
        elif key == "Activation":
            act = _ACT.get(a.get("act_type", "relu"))
            if act is None:
                raise NotImplementedError(
                    f"Activation {a.get('act_type')!r} has no ONNX mapping")
            self._emit(act, ins, [out], name)
        elif key in ("relu",):
            self._emit("Relu", ins, [out], name)
        elif key in ("sigmoid",):
            self._emit("Sigmoid", ins, [out], name)
        elif key in ("tanh",):
            self._emit("Tanh", ins, [out], name)
        elif key == "Convolution":
            attrs = {"kernel_shape": [int(k) for k in a["kernel"]],
                     "group": int(a.get("num_group", 1))}
            if a.get("stride"):
                attrs["strides"] = [int(s) for s in a["stride"]]
            if a.get("dilate"):
                attrs["dilations"] = [int(d) for d in a["dilate"]]
            if a.get("pad"):
                attrs["pads"] = [int(p) for p in a["pad"]] * 2
            self._emit("Conv", ins, [out], name, attrs)
        elif key == "BatchNorm":
            if a.get("fix_gamma", True):
                # MXNet's fix_gamma=True (the default) computes with gamma=1
                # regardless of the stored values — export ones or the
                # consumer scales by garbage
                garr = self.params.get(ins[1])
                if garr is None:
                    raise ValueError(
                        f"BatchNorm {name!r}: fix_gamma=True needs the gamma "
                        "param to size its ones replacement")
                shape = np.asarray(
                    garr.asnumpy() if hasattr(garr, "asnumpy") else garr).shape
                ins[1] = self._add_init(f"{name}_fixed_gamma",
                                        np.ones(shape, np.float32))
            self._emit("BatchNormalization", ins, [out], name,
                       {"epsilon": float(a.get("eps", 1e-3)),   # MXNet default
                        "momentum": float(a.get("momentum", 0.9))})
        elif key == "Pooling":
            ptype = a.get("pool_type", "max")
            if a.get("global_pool", False):
                op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
                self._emit(op, ins, [out], name)
            else:
                op = {"max": "MaxPool", "avg": "AveragePool"}[ptype]
                attrs = {"kernel_shape": [int(k) for k in a["kernel"]]}
                if a.get("stride"):
                    attrs["strides"] = [int(s) for s in a["stride"]]
                if a.get("pad"):
                    attrs["pads"] = [int(p) for p in a["pad"]] * 2
                if ptype == "avg":
                    attrs["count_include_pad"] = int(
                        a.get("count_include_pad", True))
                self._emit(op, ins, [out], name, attrs)
        elif key == "softmax":
            self._emit("Softmax", ins, [out], name,
                       {"axis": int(a.get("axis", -1))})
        elif key in ("SoftmaxOutput", "Softmax"):
            # legacy loss head ("Softmax" is its alias): drop the label
            # input; multi_output mode softmaxes over axis 1
            self._emit("Softmax", ins[:1], [out], name,
                       {"axis": 1 if a.get("multi_output", False) else -1})
        elif key in ("Flatten", "flatten"):
            self._emit("Flatten", ins, [out], name, {"axis": 1})
        elif key in _ELEMWISE:
            self._emit(_ELEMWISE[key], ins, [out], name)
        elif key in ("Concat", "concat"):
            self._emit("Concat", ins, [out], name,
                       {"axis": int(a.get("dim", 1))})
        elif key in ("Reshape", "reshape"):
            shp = self._add_init(f"{name}_shape",
                                 np.asarray(a["shape"], np.int64))
            self._emit("Reshape", ins + [shp], [out], name)
        elif key == "transpose":
            self._emit("Transpose", ins, [out], name,
                       {"perm": [int(x) for x in a.get("axes", ())]})
        elif key == "clip":
            lo = self._add_init(f"{name}_min",
                                np.float32(a.get("a_min", -np.inf)))
            hi = self._add_init(f"{name}_max",
                                np.float32(a.get("a_max", np.inf)))
            self._emit("Clip", ins + [lo, hi], [out], name)
        elif key == "Dropout":
            self._emit("Identity", ins[:1], [out], name)
        else:
            raise NotImplementedError(
                f"Symbol op {key!r} (node {name!r}) has no ONNX translation")

    # -- driver ------------------------------------------------------------
    def run(self) -> bytes:
        from ...symbol.symbol import _topo
        nodes = _topo(self.sym._heads)
        # which op-parameter slots consume each variable: a var used ONLY as
        # a loss-head 'label' doesn't export (the head becomes plain Softmax)
        slots: Dict[int, set] = {}
        for n in nodes:
            for (child, _), pname in zip(n.inputs, n.input_params):
                slots.setdefault(id(child), set()).add(pname)
        for node in nodes:
            if node.op_key is None:
                if node.name in self.params:
                    self._add_init(node.name, self.params[node.name])
                elif slots.get(id(node)) == {"label"}:
                    continue               # loss-head labels don't export
                else:
                    shape = self.input_shapes.get(node.name)
                    if shape is None:
                        raise ValueError(
                            f"no shape for graph input {node.name!r}: pass "
                            f"input_shapes={{{node.name!r}: (...)}} or "
                            "include it in params")
                    self.inputs.append(P.w_value_info(node.name, shape))
            else:
                self._convert(node)
        outs = [P.w_value_info(self._tname(n, j), None)
                for n, j in self.sym._heads]
        return P.w_model(self.nodes, self.initializers, self.inputs, outs)


def export_model(sym, params: Dict, input_shapes: Dict,
                 onnx_file: Optional[str] = None,
                 aux_params: Optional[Dict] = None):
    """Symbol + params → ONNX ModelProto bytes; written to ``onnx_file`` when
    given (reference export_model API, mx2onnx/export_model.py:95). ``params``
    holds arg params; ``aux_params`` (BatchNorm running stats) merge in."""
    merged = dict(params)
    if aux_params:
        merged.update(aux_params)
    data = _Exporter(sym, merged, input_shapes).run()
    if onnx_file:
        with open(onnx_file, "wb") as f:
            f.write(data)
        return onnx_file
    return data
