"""Minimal protobuf wire-format reader for the ONNX ModelProto subset.

The environment ships no ``onnx`` package, so the importer parses the wire
format directly (protobuf encoding is stable and documented: tag =
(field_number << 3) | wire_type; wire types 0 varint / 1 fixed64 /
2 length-delimited / 5 fixed32). Only the fields the op importer consumes are
modeled — unknown fields are skipped by wire type, so files from any ONNX
producer parse.

Field numbers follow onnx/onnx.proto (the public schema):
ModelProto{graph=7, opset_import=8}; GraphProto{node=1, name=2, initializer=5,
input=11, output=12}; NodeProto{input=1, output=2, name=3, op_type=4,
attribute=5}; AttributeProto{name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
type=20}; TensorProto{dims=1, data_type=2, float_data=4, int32_data=5,
int64_data=7, name=8, raw_data=9}; ValueInfoProto{name=1, type=2};
OperatorSetIdProto{domain=1, version=2}.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _fields(buf: memoryview) -> Dict[int, List[Tuple[int, object]]]:
    """One message level: field number -> [(wire_type, raw value), ...]."""
    out: Dict[int, List[Tuple[int, object]]] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _read_varint(buf, pos)
        elif wt == 1:
            val = bytes(buf[pos:pos + 8])
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(fnum, []).append((wt, val))
    return out


def _signed(v: int) -> int:
    """Protobuf int64 varints are two's-complement in 64 bits."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _ints(entries) -> List[int]:
    """Repeated int64: packed (wire 2) or unpacked varints."""
    out = []
    for wt, v in entries:
        if wt == 0:
            out.append(_signed(v))
        else:
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed(x))
    return out


_TENSOR_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
                  7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


class Tensor:
    __slots__ = ("name", "array")

    def __init__(self, name: str, array: np.ndarray):
        self.name, self.array = name, array


def parse_tensor(buf: memoryview) -> Tensor:
    f = _fields(buf)
    dims = _ints(f.get(1, []))
    (_, dt), = f.get(2, [(0, 1)])
    dtype = _TENSOR_DTYPES.get(dt)
    if dtype is None:
        raise ValueError(f"unsupported ONNX tensor data_type {dt}")
    name = bytes(f[8][0][1]).decode() if 8 in f else ""
    if 9 in f:                                        # raw_data
        arr = np.frombuffer(bytes(f[9][0][1]), dtype)
    elif 4 in f:                                      # float_data (packed f32)
        raw = b"".join(bytes(v) for _, v in f[4])
        arr = np.frombuffer(raw, np.float32).astype(dtype)
    elif 7 in f:                                      # int64_data
        arr = np.asarray(_ints(f[7]), np.int64).astype(dtype)
    elif 5 in f:                                      # int32_data
        arr = np.asarray(_ints(f[5]), np.int32).astype(dtype)
    else:
        arr = np.zeros(0, dtype)
    return Tensor(name, arr.reshape(dims).copy())


class Attribute:
    __slots__ = ("name", "value")

    def __init__(self, name, value):
        self.name, self.value = name, value


def parse_attribute(buf: memoryview) -> Attribute:
    f = _fields(buf)
    name = bytes(f[1][0][1]).decode()
    atype = f[20][0][1] if 20 in f else None
    if atype == 1 or (atype is None and 2 in f):      # FLOAT
        return Attribute(name, struct.unpack("<f", f[2][0][1])[0])
    if atype == 2 or (atype is None and 3 in f):      # INT
        return Attribute(name, _signed(f[3][0][1]))
    if atype == 3 or (atype is None and 4 in f):      # STRING
        return Attribute(name, bytes(f[4][0][1]).decode())
    if atype == 4 or (atype is None and 5 in f):      # TENSOR
        return Attribute(name, parse_tensor(f[5][0][1]))
    if atype == 6 or (atype is None and 7 in f):      # FLOATS
        raw = b"".join(bytes(v) for _, v in f.get(7, []))
        return Attribute(name, list(np.frombuffer(raw, np.float32)))
    if atype == 7 or (atype is None and 8 in f):      # INTS
        return Attribute(name, _ints(f.get(8, [])))
    return Attribute(name, None)


class Node:
    __slots__ = ("op_type", "name", "inputs", "outputs", "attrs")

    def __init__(self, op_type, name, inputs, outputs, attrs):
        self.op_type, self.name = op_type, name
        self.inputs, self.outputs, self.attrs = inputs, outputs, attrs


class Graph:
    __slots__ = ("name", "nodes", "initializers", "inputs", "outputs")

    def __init__(self, name, nodes, initializers, inputs, outputs):
        self.name = name
        self.nodes = nodes
        self.initializers = initializers                # name -> np.ndarray
        self.inputs = inputs                            # [(name, shape|None)]
        self.outputs = outputs                          # [name]


def _value_info(buf: memoryview):
    f = _fields(buf)
    name = bytes(f[1][0][1]).decode() if 1 in f else ""
    shape = None
    if 2 in f:                                          # TypeProto
        tf = _fields(f[2][0][1])
        if 1 in tf:                                     # tensor_type
            tt = _fields(tf[1][0][1])
            if 2 in tt:                                 # shape
                dims = []
                sf = _fields(tt[2][0][1])
                for _, dbuf in sf.get(1, []):
                    df = _fields(dbuf)
                    dims.append(df[1][0][1] if 1 in df else None)
                shape = tuple(dims)
    return name, shape


def parse_graph(buf: memoryview) -> Graph:
    f = _fields(buf)
    name = bytes(f[2][0][1]).decode() if 2 in f else ""
    nodes = []
    for _, nbuf in f.get(1, []):
        nf = _fields(nbuf)
        nodes.append(Node(
            bytes(nf[4][0][1]).decode() if 4 in nf else "",
            bytes(nf[3][0][1]).decode() if 3 in nf else "",
            [bytes(v).decode() for _, v in nf.get(1, [])],
            [bytes(v).decode() for _, v in nf.get(2, [])],
            {a.name: a.value for a in
             (parse_attribute(abuf) for _, abuf in nf.get(5, []))}))
    inits = {}
    for _, tbuf in f.get(5, []):
        t = parse_tensor(tbuf)
        inits[t.name] = t.array
    inputs = [_value_info(v) for _, v in f.get(11, [])]
    outputs = [_value_info(v)[0] for _, v in f.get(12, [])]
    return Graph(name, nodes, inits, inputs, outputs)


def parse_model(data: bytes) -> Tuple[Graph, int]:
    """Returns (graph, opset_version) from ModelProto bytes."""
    f = _fields(memoryview(data))
    if 7 not in f:
        raise ValueError("not an ONNX ModelProto (no graph field)")
    opset = 0
    for _, obuf in f.get(8, []):
        of = _fields(obuf)
        domain = bytes(of[1][0][1]).decode() if 1 in of else ""
        if domain in ("", "ai.onnx") and 2 in of:
            opset = of[2][0][1]
    return parse_graph(f[7][0][1]), opset


# ---------------------------------------------------------------------------
# writer — the inverse wire encoding, for mx2onnx export
# ---------------------------------------------------------------------------


def _varint(x: int) -> bytes:
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def w_varint(field: int, value: int) -> bytes:
    if value < 0:
        value += 1 << 64                     # two's-complement int64
    return _varint(field << 3) + _varint(value)


def w_bytes(field: int, data: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(data)) + data


def w_str(field: int, s: str) -> bytes:
    return w_bytes(field, s.encode())


def w_f32(field: int, v: float) -> bytes:
    return _varint((field << 3) | 5) + struct.pack("<f", v)


_NP_TO_ONNX_DTYPE = {np.dtype(v): k for k, v in _TENSOR_DTYPES.items()}


def w_tensor(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    dt = _NP_TO_ONNX_DTYPE.get(arr.dtype)
    if dt is None:
        raise ValueError(f"tensor {name!r}: unsupported dtype {arr.dtype}")
    out = b"".join(w_varint(1, int(d)) for d in arr.shape)
    out += w_varint(2, dt)
    out += w_str(8, name)
    out += w_bytes(9, arr.tobytes())
    return out


def w_attr(name: str, value) -> bytes:
    """AttributeProto with the explicit type tag (field 20)."""
    out = w_str(1, name)
    if isinstance(value, bool):
        value = int(value)
    if isinstance(value, float):
        out += w_f32(2, value) + w_varint(20, 1)             # FLOAT
    elif isinstance(value, int):
        out += w_varint(3, value) + w_varint(20, 2)          # INT
    elif isinstance(value, str):
        out += w_bytes(4, value.encode()) + w_varint(20, 3)  # STRING
    elif isinstance(value, (list, tuple)) and value \
            and all(isinstance(v, float) for v in value):
        out += b"".join(w_f32(7, v) for v in value) + w_varint(20, 6)
    elif isinstance(value, (list, tuple)):
        out += b"".join(w_varint(8, int(v)) for v in value) + w_varint(20, 7)
    else:
        raise TypeError(f"attr {name!r}: unsupported value {value!r}")
    return out


def w_node(op_type: str, inputs, outputs, name: str = "", attrs=None) -> bytes:
    out = b"".join(w_str(1, i) for i in inputs)
    out += b"".join(w_str(2, o) for o in outputs)
    if name:
        out += w_str(3, name)
    out += w_str(4, op_type)
    for k, v in (attrs or {}).items():
        out += w_bytes(5, w_attr(k, v))
    return out


def w_value_info(name: str, shape=None, elem_type: int = 1) -> bytes:
    tt = w_varint(1, elem_type)
    if shape is not None:
        dims = b""
        for i, d in enumerate(shape):
            if d is None or isinstance(d, str):
                # dynamic dimension → dim_param (Dimension field 2)
                dims += w_bytes(1, w_str(2, d if isinstance(d, str)
                                         else f"dyn_{i}"))
            else:
                dims += w_bytes(1, w_varint(1, int(d)))
        tt += w_bytes(2, dims)
    return w_str(1, name) + w_bytes(2, w_bytes(1, tt))


def w_model(nodes, initializers, inputs, outputs, graph_name: str = "mxtpu",
            opset: int = 13, producer: str = "mxtpu") -> bytes:
    """nodes: encoded NodeProto bytes; initializers: encoded TensorProto
    bytes; inputs/outputs: encoded ValueInfoProto bytes."""
    graph = b"".join(w_bytes(1, n) for n in nodes)
    graph += w_str(2, graph_name)
    graph += b"".join(w_bytes(5, t) for t in initializers)
    graph += b"".join(w_bytes(11, v) for v in inputs)
    graph += b"".join(w_bytes(12, v) for v in outputs)
    model = w_varint(1, 8)                              # ir_version
    model += w_str(2, producer)
    model += w_bytes(7, graph)
    model += w_bytes(8, w_varint(2, opset))             # opset_import
    return model
