"""Torch plugin bridge — run PyTorch (CPU) code as first-class framework ops.

Reference capability: ``plugin/torch`` (TorchModule/torch criterion as MXNet
operators; mxnet.torch namespace) — users bring a foreign framework's kernels
into the graph. The TPU-native analog: ``register_torch_op`` wraps a torch
function as a REAL registry op — visible as ``mx.nd.<name>`` and
``mx.sym.<name>``, usable eagerly, inside ``hybridize``/``jit`` (it lowers to
``jax.pure_callback``, so the torch code runs host-side while the surrounding
program stays compiled), and differentiable: the backward is computed by
``torch.autograd`` inside a second callback, spliced in via ``jax.custom_vjp``.

This is the same machinery as ``mxtpu.operator.CustomOp`` (custom-inl.h role),
pointed at torch instead of user numpy — proving the escape hatch composes
with a real foreign framework.

Constraints (documented, reference-parity): the torch fn must be a pure
tensor→tensor(s) function (no hidden state), CPU torch, float tensors.
Backends without host-callback support (e.g. tunneled PJRT plugins like axon)
get the eager forward via a CPU-backend hop; in-jit use and tape backward
there raise with guidance — standard TPU/CPU runtimes support everything.
"""

from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import numpy as np

__all__ = ["register_torch_op", "TorchOp"]


def _require_torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is baked into the image
        raise ImportError("the torch bridge needs pytorch installed") from e
    return torch


_CB_SUPPORT = None


def _callbacks_supported() -> bool:
    """Whether the default backend can run host callbacks. Standard TPU/CPU
    runtimes can; some tunneled PJRT plugins cannot (e.g. axon reports
    UNIMPLEMENTED host send/recv) — there the op runs on the CPU backend and
    results transfer back."""
    global _CB_SUPPORT
    if _CB_SUPPORT is None:
        import jax
        try:
            jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct((), np.float32),
                jax.numpy.float32(0.0))
            _CB_SUPPORT = True
        except Exception:
            _CB_SUPPORT = False
    return _CB_SUPPORT


class TorchOp:
    """A torch function wrapped as a differentiable jax-compatible callable."""

    def __init__(self, fn: Callable, name: str = "torch_op"):
        self.fn = fn
        self.name = name
        self._out_struct: Dict[tuple, tuple] = {}  # sig -> (shapes, dtypes, single)
        self._build()

    # -- host-side executions (inside pure_callback) -----------------------
    @staticmethod
    def _to_torch(torch, a):
        a = np.ascontiguousarray(a)
        if not a.flags.writeable:       # jax buffers are read-only views
            a = a.copy()
        return torch.from_numpy(a)

    def _run_fwd(self, *arrays):
        torch = _require_torch()
        with torch.no_grad():
            outs = self.fn(*[self._to_torch(torch, a) for a in arrays])
        single = not isinstance(outs, (tuple, list))
        outs = [outs] if single else list(outs)
        return [o.detach().numpy() for o in outs], single

    def _run_bwd(self, arrays, cots):
        torch = _require_torch()
        tins = [self._to_torch(torch, a).requires_grad_(True)
                for a in arrays]
        outs = self.fn(*tins)
        outs = [outs] if not isinstance(outs, (tuple, list)) else list(outs)
        gouts = [self._to_torch(torch, c) for c in cots]
        grads = torch.autograd.grad(outs, tins, grad_outputs=gouts,
                                    allow_unused=True)
        return [np.zeros(a.shape, a.dtype) if g is None else
                g.detach().numpy().astype(a.dtype, copy=False)
                for g, a in zip(grads, arrays)]

    def _struct_for(self, args) -> tuple:
        """Output (shapes, dtypes, single) per input signature — probed once by
        running the torch fn on zeros host-side (the fn must be shape-pure)."""
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        got = self._out_struct.get(sig)
        if got is None:
            probe = [np.zeros(s, np.dtype(d)) for s, d in sig]
            outs, single = self._run_fwd(*probe)
            got = (tuple(o.shape for o in outs),
                   tuple(o.dtype for o in outs), single)
            self._out_struct[sig] = got
        return got

    # -- the jax-facing callable -------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        op = self

        @jax.custom_vjp
        def call(*args):
            shapes, dtypes, _ = op._struct_for(args)
            result_shape = tuple(jax.ShapeDtypeStruct(s, d)
                                 for s, d in zip(shapes, dtypes))
            outs = jax.pure_callback(
                lambda *a: tuple(op._run_fwd(*[np.asarray(x) for x in a])[0]),
                result_shape, *args, vmap_method="sequential")
            return outs

        def fwd(*args):
            return call(*args), args

        def bwd(res, cots):
            in_struct = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                              for a in res)
            grads = jax.pure_callback(
                lambda inputs, gs: tuple(op._run_bwd(
                    [np.asarray(x) for x in inputs],
                    [np.asarray(g) for g in gs])),
                in_struct, res, cots, vmap_method="sequential")
            return tuple(grads)

        call.defvjp(fwd, bwd)
        self._pure_call = call

    def _call(self, *args):
        """Backend-aware dispatch: native pure_callback where supported, else
        hop through the CPU backend (differentiable: device transfers have
        transfer transposes)."""
        import jax
        if _callbacks_supported():
            return self._pure_call(*args)
        if any(isinstance(a, jax.core.Tracer) for a in args):
            raise NotImplementedError(
                f"torch-bridge op {self.name!r}: this backend does not "
                "support host callbacks, so the op cannot run inside jit — "
                "call it eagerly (outside hybridize/jit)")
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            outs = self._pure_call(*[jax.device_put(a, cpu) for a in args])
        return tuple(jax.device_put(o) for o in outs)

    def __call__(self, *args):
        import jax.numpy as jnp
        raw = [a.data if hasattr(a, "data") and not isinstance(a, np.ndarray)
               else jnp.asarray(a) for a in args]
        outs = self._call(*raw)
        _, _, single = self._struct_for(raw)
        return outs[0] if single else tuple(outs)


def register_torch_op(name: str, fn: Callable, namespace: str = "contrib",
                      num_outputs: int = 1):
    """Register ``fn`` (torch tensors in → tensor(s) out) as a framework op.

    After this, ``mx.nd.contrib.<name>`` / ``mx.sym.contrib.<name>`` exist like
    any built-in op (mxnet.torch namespace parity). Returns the TorchOp.
    Multi-output fns must declare ``num_outputs`` so the symbolic frontend
    exposes every head (the nd path detects the tuple dynamically).
    """
    from ..ops import registry as _reg

    top = TorchOp(fn, name)

    def op_fn(*args):
        outs = top._call(*args)
        # single-ness is static per input signature (probed host-side), so
        # this branch resolves at trace time
        _, _, single = top._struct_for(args)
        return outs[0] if single else outs

    op_fn.__name__ = name
    op_fn.__doc__ = f"torch-bridge op {name!r} (plugin/torch parity)"
    _reg.register(f"{namespace}.{name}" if namespace else name,
                  num_outputs=num_outputs)(op_fn)

    # surface on the already-built nd/sym namespaces
    from .. import ndarray as nd_pkg
    from .. import symbol as sym_pkg
    from ..symbol.symbol import make_op_wrapper
    key = f"{namespace}.{name}" if namespace else name
    opdef = _reg.get_op(key)

    def nd_wrapper(*args, **kwargs):
        return _reg.invoke(opdef, *args, **kwargs)

    nd_wrapper.__name__ = name
    target_nd = getattr(nd_pkg, namespace) if namespace else nd_pkg
    target_sym = getattr(sym_pkg, namespace) if namespace else sym_pkg
    setattr(target_nd, name, nd_wrapper)
    setattr(target_sym, name, make_op_wrapper(key))
    return top
