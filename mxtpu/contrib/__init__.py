"""Contrib python packages (parity with ``python/mxnet/contrib``): quantization
driver here; contrib ops live under ``mxtpu.nd.contrib`` (ops/contrib_ops.py)."""

from . import quantization  # noqa: F401
from . import text  # noqa: F401
