"""Contrib python packages (parity with ``python/mxnet/contrib``): quantization
driver here; contrib ops live under ``mxtpu.nd.contrib`` (ops/contrib_ops.py);
the torch plugin bridge (plugin/torch parity) is ``torch_bridge`` (torch itself
is only imported at first use inside it)."""

from . import onnx  # noqa: F401
from . import quantization  # noqa: F401
from . import text  # noqa: F401
from . import torch_bridge  # noqa: F401
