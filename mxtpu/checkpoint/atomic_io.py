"""Atomic filesystem primitives for the checkpoint subsystem.

The reference's ``save_checkpoint`` (python/mxnet/model.py:384) writes straight
into the destination file — a SIGKILL mid-``nd.save`` leaves a torn ``.params``
and the run is unrecoverable. Every byte the checkpoint subsystem persists goes
through the two primitives here instead:

* **file atomicity** — ``atomic_write``/``atomic_write_bytes``: write into a
  tempfile in the destination directory, flush + ``fsync``, then ``os.replace``
  (atomic on POSIX within a filesystem), then fsync the directory so the rename
  itself is durable. A crash at ANY point leaves either the old file or the new
  file, never a hybrid.

* **directory commit protocol** — ``commit_dir``: a checkpoint is staged as
  ``step-N.tmp/``, every file in it fsynced, the directory renamed to
  ``step-N/``, and only then is a ``COMMIT`` marker dropped (itself atomically).
  Readers (``committed_steps``) require the marker, so a crash before the
  marker — including between the rename and the marker write — leaves a dir
  that discovery ignores. Restore can never observe a torn checkpoint.

This module deliberately has NO mxtpu imports so low layers (``ndarray.save``)
can use it without an import cycle.
"""

from __future__ import annotations

import os
import re
import shutil
import tempfile
from typing import Callable, Iterable, List, Optional

COMMIT_MARKER = "COMMIT"
TMP_SUFFIX = ".tmp"

_STEP_RE = re.compile(r"^(?P<prefix>.+)-(?P<step>\d+)$")


def fsync_path(path: str):
    """fsync a file or directory by path (durability of the entry itself)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir_of(path: str):
    """fsync the parent directory so a rename/create of ``path`` is durable."""
    fsync_path(os.path.dirname(os.path.abspath(path)) or ".")


def atomic_write(fname: str, write_fn: Callable, fsync: bool = True) -> int:
    """Write via ``write_fn(file_obj)`` into a same-directory tempfile, fsync,
    and ``os.replace`` over the destination. Returns bytes written.

    Same-directory matters twice: ``os.replace`` must not cross filesystems,
    and a crash leaves the debris next to the target where the next save's
    stale-tmp sweep (or the operator) can see it.
    """
    fname = os.path.abspath(fname)
    d = os.path.dirname(fname)
    fd, tmp = tempfile.mkstemp(dir=d, prefix="." + os.path.basename(fname) + ".",
                               suffix=TMP_SUFFIX)
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
            nbytes = f.tell()
        os.replace(tmp, fname)
        if fsync:
            fsync_path(d)
        return nbytes
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_bytes(fname: str, data: bytes, fsync: bool = True) -> int:
    return atomic_write(fname, lambda f: f.write(data), fsync=fsync)


# ---------------------------------------------------------------------------
# directory commit protocol
# ---------------------------------------------------------------------------


def staging_dir(root: str, name: str) -> str:
    """Create (or reuse) the staging directory ``root/name.tmp/``."""
    path = os.path.join(root, name + TMP_SUFFIX)
    os.makedirs(path, exist_ok=True)
    return path


def commit_dir(root: str, name: str, fsync: bool = True,
               hooks: Optional[dict] = None) -> str:
    """Promote ``root/name.tmp/`` to the committed ``root/name/``.

    Protocol: fsync every file in the staging dir, fsync the staging dir,
    rename to the final name, fsync the parent, then atomically drop the
    ``COMMIT`` marker inside. ``hooks`` is a test seam: callables under
    ``"before_rename"`` / ``"before_marker"`` run at the matching point so
    crash-mid-save tests can kill the writer at either window.
    """
    hooks = hooks or {}
    tmp = os.path.join(root, name + TMP_SUFFIX)
    final = os.path.join(root, name)
    if fsync:
        for entry in os.scandir(tmp):
            if entry.is_file():
                fsync_path(entry.path)
        fsync_path(tmp)
    if "before_rename" in hooks:
        hooks["before_rename"]()
    if os.path.isdir(final):        # a previous torn commit of the same step
        shutil.rmtree(final)
    os.replace(tmp, final)
    if fsync:
        fsync_path(root)
    if "before_marker" in hooks:
        hooks["before_marker"]()
    atomic_write_bytes(os.path.join(final, COMMIT_MARKER), b"1\n", fsync=fsync)
    return final


def is_committed(root: str, name: str) -> bool:
    return os.path.isfile(os.path.join(root, name, COMMIT_MARKER))


def committed_steps(root: str, prefix: str = "step") -> List[int]:
    """Sorted step numbers of COMMITted ``prefix-N/`` dirs under ``root``.

    Uncommitted dirs — ``.tmp`` staging debris or a renamed dir whose writer
    died before dropping the marker — are invisible here by construction.
    """
    steps = []
    if not os.path.isdir(root):
        return steps
    for entry in os.listdir(root):
        m = _STEP_RE.match(entry)
        if not m or m.group("prefix") != prefix:
            continue
        if is_committed(root, entry):
            steps.append(int(m.group("step")))
    return sorted(steps)


def remove_step(root: str, prefix: str, step: int):
    """GC one committed step: drop the marker FIRST (atomic un-commit), then
    the payload — a crash mid-delete leaves an uncommitted dir, not a
    half-valid checkpoint."""
    path = os.path.join(root, f"{prefix}-{step}")
    marker = os.path.join(path, COMMIT_MARKER)
    try:
        os.unlink(marker)
    except FileNotFoundError:
        pass
    shutil.rmtree(path, ignore_errors=True)


def sweep_stale_staging(root: str, prefix: str = "step",
                        keep: Iterable[str] = ()) -> List[str]:
    """Delete ``prefix-*.tmp`` staging debris left by dead writers."""
    removed = []
    keep = set(keep)
    if not os.path.isdir(root):
        return removed
    for entry in os.listdir(root):
        if not entry.endswith(TMP_SUFFIX):
            continue
        stem = entry[:-len(TMP_SUFFIX)]
        m = _STEP_RE.match(stem)
        if not m or m.group("prefix") != prefix or entry in keep:
            continue
        shutil.rmtree(os.path.join(root, entry), ignore_errors=True)
        removed.append(entry)
    return removed


def dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for fn in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, fn))
            except OSError:
                pass
    return total
