"""Training-state snapshot: capture device state to host, and re-place it.

``capture`` walks the full training state — params, aux (BN running stats),
Trainer/optimizer slots, the framework RNG key, and the loop counters — in two
passes: it first kicks off a device→host copy of every array
(``jax.Array.copy_to_host_async``) so all DMAs overlap, then waits for them
and returns a fully HOST-RESIDENT snapshot. Blocking on the copies before
returning is load-bearing, not a convenience: the fused step executor and the
optimizer donate their input buffers (``step_cache``/``optimizer``
``donate_argnums``), so the next training step deletes the device arrays a
reference-only snapshot would still point at. The training thread therefore
pays only for the overlapped DMA; serialize+fsync+commit still happen on the
background writer (the Orbax/TF-CheckpointManager split).

``apply_*`` are the duals: they push host arrays back into a live module /
trainer, re-placing each array with its saved ``NamedSharding`` spec through
``parallel.data_parallel._place`` (the same host→mesh placement the training
step uses), so a restored run resumes with identical layout.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

FORMAT_VERSION = 1


def _dtype_from_str(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def spec_of(x) -> Optional[list]:
    """JSON-able partition spec of a NamedSharding-placed array, else None."""
    from jax.sharding import NamedSharding
    sh = getattr(x, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return None
    spec = tuple(sh.spec)
    if not any(s is not None for s in spec):
        return None
    return [list(s) if isinstance(s, tuple) else s for s in spec]


def _spec_to_partition(spec: list):
    from jax.sharding import PartitionSpec as P
    return P(*[tuple(s) if isinstance(s, list) else s for s in spec])


def _start_host_copy(x):
    """Kick off the device→host DMA without waiting for it."""
    try:
        x.copy_to_host_async()
    except (AttributeError, RuntimeError):
        pass
    return x


def _to_host(x) -> np.ndarray:
    """Materialize one array on the host. Multi-process arrays yield this
    process's LOCAL data (deduped addressable shards, concatenated along the
    sharded axis) — the inverse of ``_place``'s per-host-feed convention."""
    import jax
    if isinstance(x, np.ndarray):
        return x
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(jax.device_get(x))
    uniq: Dict[tuple, Any] = {}
    for s in x.addressable_shards:
        key = tuple((sl.start or 0, sl.stop) for sl in s.index)
        uniq.setdefault(key, s)
    shards = sorted(uniq.values(),
                    key=lambda s: tuple(sl.start or 0 for sl in s.index))
    if len(shards) == 1:
        return np.asarray(jax.device_get(shards[0].data))
    starts = [tuple(sl.start or 0 for sl in s.index) for s in shards]
    axis = next((d for d in range(len(starts[0]))
                 if len({st[d] for st in starts}) > 1), 0)
    return np.concatenate(
        [np.asarray(jax.device_get(s.data)) for s in shards], axis=axis)


def _short_names(block):
    """name -> Parameter with the block prefix stripped (Module.get_params
    convention, so snapshots match the legacy arg/aux key space)."""
    out = {}
    for name, p in block.collect_params().items():
        short = name[len(block.prefix):] if name.startswith(block.prefix) \
            else name
        out[short] = p
    return out


class TrainingSnapshot:
    """One captured training state: ``arrays`` (key -> device handle or host
    ndarray) plus JSON-able ``meta`` (counters, shardings, dtypes, rng)."""

    def __init__(self, arrays: Dict[str, Any], meta: Dict[str, Any]):
        self.arrays = arrays
        self.meta = meta

    def materialize(self) -> "TrainingSnapshot":
        """Idempotent safety net: ``capture`` already lands every array on the
        host, so this is a no-op for its snapshots; hand-built snapshots that
        still hold device arrays get converted here."""
        self.arrays = {k: _to_host(v) for k, v in self.arrays.items()}
        return self

    @property
    def step(self) -> Optional[int]:
        return self.meta.get("step")


def capture(step: int, module=None, trainer=None, arg_params=None,
            aux_params=None, epoch: Optional[int] = None,
            nbatch: Optional[int] = None, include_rng: bool = True,
            extra_meta: Optional[dict] = None) -> TrainingSnapshot:
    """Snapshot the full training state (non-blocking on the device side)."""
    import jax

    arrays: Dict[str, Any] = {}
    shardings: Dict[str, list] = {}

    def _add(key, value):
        raw = value.data if hasattr(value, "asnumpy") else value
        spec = spec_of(raw)
        if spec is not None:
            shardings[key] = spec
        if not isinstance(raw, np.ndarray):
            raw = _start_host_copy(raw)
        arrays[key] = raw

    if module is not None:
        arg, aux = module.get_params()
        arg_params = arg if arg_params is None else arg_params
        aux_params = aux if aux_params is None else aux_params
        if trainer is None:
            trainer = getattr(module, "_trainer", None)
    for k, v in (arg_params or {}).items():
        _add(f"arg:{k}", v)
    for k, v in (aux_params or {}).items():
        _add(f"aux:{k}", v)

    trainer_meta = None
    if trainer is not None:
        trainer._init_kvstore()
        opt = trainer._optimizer
        state_slots: List[Optional[int]] = []
        for i, st in enumerate(trainer._states):
            if st is None:
                state_slots.append(None)
                continue
            state_slots.append(len(st))
            for j, s in enumerate(st):
                _add(f"opt:{i}:{j}", s)
        # ZeRO-1 slots: per-bucket dp-sharded flat arrays. _add records the
        # NamedSharding spec and _to_host lands the GLOBAL bucket (deduped
        # shards), so restore can re-pad for a DIFFERENT dp degree.
        zero_meta = None
        if getattr(trainer, "_zero_layout", None) is not None:
            zslots: List[int] = []
            for b, st in enumerate(trainer._zero_states):
                zslots.append(len(st))
                for j, s in enumerate(st):
                    _add(f"zopt:{b}:{j}", s)
            for b, r in enumerate(trainer._zero_residuals or []):
                if r is not None:
                    _add(f"zres:{b}", r)
            zero_meta = {"layout": trainer._zero_layout.describe(),
                         "slots": zslots}
        trainer_meta = {
            "optimizer": type(opt).__name__,
            "num_update": int(opt.num_update),
            "counts": {str(k): int(v)
                       for k, v in opt._index_update_count.items()},
            "state_slots": state_slots,
            "zero": zero_meta,
        }

    rng_meta = None
    if include_rng:
        from .. import rng as rng_mod
        blob = rng_mod.get_state_blob()
        arrays["rng:key_data"] = blob["key_data"]
        rng_meta = {"trace_counter": blob["trace_counter"]}

    # Wait on the in-flight copies and land everything on the host before
    # returning: the caller's next training step may donate (and delete) the
    # device buffers these entries reference (step_cache/optimizer
    # donate_argnums), so the snapshot must not outlive them on device.
    arrays = {k: _to_host(v) for k, v in arrays.items()}

    meta = {
        "format": FORMAT_VERSION,
        "step": int(step),
        "epoch": None if epoch is None else int(epoch),
        "nbatch": None if nbatch is None else int(nbatch),
        "process_count": jax.process_count(),
        "shardings": shardings,
        "trainer": trainer_meta,
        "rng": rng_meta,
    }
    if extra_meta:
        meta["extra"] = dict(extra_meta)
    return TrainingSnapshot(arrays, meta)


# ---------------------------------------------------------------------------
# restore duals
# ---------------------------------------------------------------------------


def _needs_mesh(snapshot: TrainingSnapshot) -> bool:
    return bool(snapshot.meta.get("shardings"))


def _filter_spec_for_mesh(spec: list, mesh) -> list:
    """Drop spec entries naming axes the current mesh doesn't have — an
    fsdp8 snapshot restored onto a dp-only (or narrower) mesh falls back to
    replicated on those dims instead of raising. Saved arrays are global
    (``_to_host`` gathers), so re-placement with fewer/renamed axes is just
    a different slicing of the same full array."""
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = [a for a in entry if a in names]
            return kept if kept else None
        return entry if entry in names else None

    return [keep(e) for e in spec]


def restored_array(snapshot: TrainingSnapshot, key: str, mesh=None):
    """One array back on device, re-placed with its saved sharding spec
    (via ``parallel.data_parallel._place``) when one was recorded. Spec
    entries naming mesh axes that no longer exist (elastic restarts can
    shrink or rename the fsdp axis) degrade to replicated on that dim."""
    import jax.numpy as jnp
    raw = snapshot.arrays[key]
    spec = snapshot.meta.get("shardings", {}).get(key)
    if spec is not None and mesh is not None:
        from jax.sharding import NamedSharding
        from ..parallel.data_parallel import _place
        spec = _filter_spec_for_mesh(spec, mesh)
        return _place(raw, NamedSharding(mesh, _spec_to_partition(spec)))
    return jnp.asarray(raw)


def default_mesh_for(snapshot: TrainingSnapshot):
    if not _needs_mesh(snapshot):
        return None
    from ..parallel.mesh import get_default_mesh
    return get_default_mesh()


def apply_params(snapshot: TrainingSnapshot, module, mesh=None,
                 allow_missing: bool = False):
    """Push arg/aux arrays into an initialized Module's parameters.

    Matching is by name first (the legacy arg/aux key space). Block names
    carry per-process instance counters (``conv2d0_`` vs ``conv2d1_``), so a
    same-process re-instantiation of the same architecture gets fresh names;
    unmatched params fall back to POSITIONAL matching within the arg/aux
    group (collect_params order is construction order), gated on exact shape
    agreement."""
    import warnings
    from ..ndarray.ndarray import NDArray
    mesh = mesh if mesh is not None else default_mesh_for(snapshot)
    named = _short_names(module._block)
    live = [(short, p) for short, p in named.items() if p._data is not None]
    grouped = {"arg:": [(s, p) for s, p in live if p.grad_req != "null"],
               "aux:": [(s, p) for s, p in live if p.grad_req == "null"]}
    saved = {pre: [k for k in snapshot.arrays if k.startswith(pre)]
             for pre in ("arg:", "aux:")}
    missing = []
    fell_back = False
    for pre, group in grouped.items():
        by_name = set(saved[pre])
        positional_ok = len(group) == len(saved[pre]) and all(
            tuple(snapshot.arrays[k].shape) == p._data.shape
            for k, (_s, p) in zip(saved[pre], group))
        for idx, (short, p) in enumerate(group):
            key = pre + short
            if key not in by_name:
                if positional_ok:
                    key = saved[pre][idx]
                    fell_back = True
                else:
                    missing.append(short)
                    continue
            p.set_data(NDArray(restored_array(snapshot, key, mesh)))
    if fell_back:
        warnings.warn(
            "checkpoint restore matched some parameters positionally (block "
            "instance counters differ from save time); shapes agreed",
            stacklevel=2)
    if missing and not allow_missing:
        raise KeyError(f"checkpoint is missing parameters {missing}; pass "
                       "allow_missing=True to restore a partial state")
    return missing


def apply_trainer(snapshot: TrainingSnapshot, trainer, mesh=None):
    """Push optimizer slots + update counters back into a Trainer."""
    import warnings
    tmeta = snapshot.meta.get("trainer")
    if tmeta is None:
        return
    mesh = mesh if mesh is not None else default_mesh_for(snapshot)
    trainer._init_kvstore()
    opt = trainer._optimizer
    if tmeta.get("optimizer") and tmeta["optimizer"] != type(opt).__name__:
        warnings.warn(
            f"checkpoint optimizer state was saved by {tmeta['optimizer']} "
            f"but is being restored into {type(opt).__name__}; slots are "
            "applied positionally", stacklevel=2)
    slots = tmeta.get("state_slots", [])
    states: List[Optional[tuple]] = []
    for i in range(len(trainer._params)):
        n = slots[i] if i < len(slots) else None
        if n is None:
            states.append(None)
        else:
            states.append(tuple(
                restored_array(snapshot, f"opt:{i}:{j}", mesh)
                for j in range(n)))
    trainer._states = states
    zmeta = tmeta.get("zero")
    if zmeta is not None:
        # the bucket layout is (re)built lazily by the fused step executor —
        # stage the host arrays; StepExecutor._ensure_zero_states adopts them
        # (stripping the saved padding and re-padding for the CURRENT dp
        # degree, so a restore onto a different mesh size re-shards instead
        # of crashing)
        zarrays = {k: np.asarray(v) for k, v in snapshot.arrays.items()
                   if k.startswith(("zopt:", "zres:"))}
        trainer._zero_restore = (zmeta, zarrays)
        trainer._zero_layout = None
        trainer._zero_states = []
        trainer._zero_residuals = []
    opt.num_update = int(tmeta.get("num_update", 0))
    opt._index_update_count = {int(k): int(v)
                               for k, v in tmeta.get("counts", {}).items()}


def apply_rng(snapshot: TrainingSnapshot):
    if snapshot.meta.get("rng") is None:
        return
    from .. import rng as rng_mod
    rng_mod.set_state_blob({
        "key_data": np.asarray(snapshot.arrays["rng:key_data"]),
        "trace_counter": snapshot.meta["rng"].get("trace_counter", 0)})
