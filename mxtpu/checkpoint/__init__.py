"""``mxtpu.checkpoint`` — fault-tolerant async checkpoint subsystem.

The production-grade replacement for the reference's save_checkpoint /
do_checkpoint helpers (python/mxnet/model.py:384, callback.py): async saves
with an atomic commit protocol, retention/GC, multi-process shard awareness,
legacy-layout compat, and a SIGTERM preemption hook. See ``manager.py`` for
the design notes and ``docs/checkpointing.md`` for the knob mapping.

Import structure: ``atomic_io`` is dependency-free and imported eagerly (low
layers like ``ndarray.save`` use it); the manager/snapshot layers import the
rest of the framework and load lazily.
"""

from . import atomic_io
from .atomic_io import committed_steps

__all__ = ["CheckpointManager", "TrainingSnapshot", "atomic_io",
           "committed_steps", "latest_step", "all_steps", "save_legacy",
           "strip_amp_cast"]

_LAZY = {
    "CheckpointManager": ("mxtpu.checkpoint.manager", "CheckpointManager"),
    "save_legacy": ("mxtpu.checkpoint.manager", "save_legacy"),
    "strip_amp_cast": ("mxtpu.checkpoint.manager", "strip_amp_cast"),
    "TrainingSnapshot": ("mxtpu.checkpoint.snapshot", "TrainingSnapshot"),
    "manager": ("mxtpu.checkpoint.manager", None),
    "snapshot": ("mxtpu.checkpoint.snapshot", None),
}


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(entry[0])
    obj = mod if entry[1] is None else getattr(mod, entry[1])
    globals()[name] = obj
    return obj


def latest_step(directory: str, step_prefix: str = "step"):
    """Newest COMMITted step under ``directory``, or None (module-level
    convenience over ``atomic_io.committed_steps``)."""
    steps = committed_steps(directory, step_prefix)
    return steps[-1] if steps else None


def all_steps(directory: str, step_prefix: str = "step"):
    return committed_steps(directory, step_prefix)
