"""CheckpointManager — fault-tolerant async checkpointing for training loops.

The reference treats checkpointing as a helper (``python/mxnet/model.py:384``
save_checkpoint + ``callback.do_checkpoint``): synchronous, non-atomic, and
blind to optimizer state, RNG, and multi-process topology. On preemptible TPU
fleets that is not a feature gap but a correctness hole — a SIGKILL
mid-``nd.save`` leaves a torn ``.params`` and the run is unrecoverable. This
module is the Orbax/TF-CheckpointManager-style answer: a manager that owns the
full training-state lifecycle.

* **async save** — ``save()`` snapshots device arrays (overlapped device→host
  DMA via ``snapshot.capture``, landed on the host before returning so buffer
  donation by the next step can't invalidate the snapshot) and hands the job
  to a background writer thread; the training step pays for the D2H copy, not
  the serialize+fsync. ``profiler`` counters record the blocked-step time,
  save latency, and committed bytes.
* **atomic commit** — the writer stages ``step-N.tmp/``, fsyncs, renames to
  ``step-N/``, then drops a ``COMMIT`` marker (``atomic_io.commit_dir``).
  ``latest_step()``/``all_steps()`` only see committed steps, so restore can
  never observe a torn checkpoint.
* **retention** — ``max_to_keep`` newest steps survive GC; ``keep_period``
  pins every N-th step forever.
* **multi-process** — each process writes its addressable shards as
  ``arrays-rK.npz``; process 0 commits after a barrier (kvstore/dist), and
  restore re-places arrays with the saved ``NamedSharding`` spec through
  ``parallel.data_parallel._place``.
* **preemption** — ``install_preemption_handler`` hooks SIGTERM to run one
  final blocking save and drain the writer before the process dies.

The legacy ``prefix-####.params`` layout remains first-class: ``save_legacy``
is the one (now atomic) writer for it, and a manager constructed with
``legacy_prefix=`` discovers and restores those files alongside native steps.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import atomic_io
from .snapshot import (TrainingSnapshot, apply_params, apply_rng,
                       apply_trainer, capture, default_mesh_for)

__all__ = ["CheckpointManager", "save_legacy", "strip_amp_cast"]

_ARRAYS_FILE = "arrays-r{rank}.npz"
_META_FILE = "meta.json"
_META_KEY = "__meta__"

# Managers that were never close()d must not swallow a latched writer error
# at interpreter exit: every live manager is tracked here and audited by an
# atexit hook (loud error log — the shutdown-time analogue of close()'s
# re-raise, since raising inside atexit can't fail the caller anymore).
_live_lock = threading.Lock()
_live_managers: "weakref.WeakSet" = None  # created on first manager


def _audit_unclosed_managers():
    with _live_lock:
        mgrs = list(_live_managers) if _live_managers is not None else []
    for m in mgrs:
        with m._lock:
            errs = list(m._errors)
        if errs:
            logging.getLogger(__name__).error(
                "CheckpointManager(%s): exiting with %d unraised async-writer "
                "error(s) — the last save(s) of this run did NOT commit. "
                "First: %s. Call close()/wait_until_finished() to surface "
                "these as exceptions.", m.directory, len(errs), errs[0])


def _track_manager(mgr: "CheckpointManager"):
    global _live_managers
    import atexit
    import weakref
    with _live_lock:
        if _live_managers is None:
            _live_managers = weakref.WeakSet()
            atexit.register(_audit_unclosed_managers)
        _live_managers.add(mgr)


class _SaveJob:
    __slots__ = ("snapshot", "done", "error", "t_enqueued")

    def __init__(self, snapshot: TrainingSnapshot):
        self.snapshot = snapshot
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.t_enqueued = time.perf_counter()


def _default_barrier():
    """Commit barrier: all processes must finish writing their shards before
    process 0 promotes the step. kvstore's dist barrier and this are the same
    primitive (a tiny psum over the pod)."""
    import jax
    if jax.process_count() > 1:
        from ..parallel import collectives
        collectives.process_barrier()


class CheckpointManager:
    """Owns a checkpoint directory: async save, atomic commit, retention,
    discovery, restore. Thread-safe for the single-trainer usage pattern
    (one training thread calling ``save``; one background writer)."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = 5,
                 keep_period: Optional[int] = None, step_prefix: str = "step",
                 legacy_prefix: Optional[str] = None,
                 barrier: Optional[Callable[[], None]] = None,
                 fsync: bool = True, logger=logging):
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.keep_period = keep_period
        self.step_prefix = step_prefix
        self.legacy_prefix = legacy_prefix
        self.fsync = fsync
        self.logger = logger
        self._barrier = barrier if barrier is not None else _default_barrier
        self._queue: "queue.Queue[Optional[_SaveJob]]" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._last_step: Optional[int] = None
        self._preempt_installed = False
        # test seam: {"before_write"|"before_rename"|"before_marker": fn} —
        # crash-mid-save tests kill the writer at the matching window
        self._test_hooks: Dict[str, Callable[[], None]] = {}
        _track_manager(self)

    # -- discovery ---------------------------------------------------------
    def all_steps(self) -> List[int]:
        """Committed steps, native layout plus legacy prefix files."""
        steps = set(atomic_io.committed_steps(self.directory,
                                              self.step_prefix))
        steps.update(self._legacy_steps())
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _legacy_steps(self) -> List[int]:
        if not self.legacy_prefix:
            return []
        import re
        base = os.path.basename(self.legacy_prefix)
        d = os.path.dirname(os.path.abspath(self.legacy_prefix)) \
            or self.directory
        pat = re.compile(re.escape(base) + r"-(\d+)\.params$")
        out = []
        if os.path.isdir(d):
            for entry in os.listdir(d):
                m = pat.match(entry)
                if m:
                    out.append(int(m.group(1)))
        return sorted(out)

    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.step_prefix}-{step}")

    # -- async save --------------------------------------------------------
    def save(self, step: int, module=None, trainer=None, arg_params=None,
             aux_params=None, epoch: Optional[int] = None,
             nbatch: Optional[int] = None, blocking: bool = False,
             include_rng: bool = True,
             extra_meta: Optional[dict] = None) -> _SaveJob:
        """Snapshot the training state and enqueue the write. Returns after
        the device→host handoff (all D2H copies overlapped and landed on the
        host, so donated device buffers may die freely afterwards) — the
        blocked-step time is recorded in the profiler's checkpoint counters.
        ``blocking=True`` additionally waits for the commit. Writer errors
        are never silent: a blocking save re-raises its own, and an async
        save's error surfaces at the NEXT ``save()`` /
        ``wait_until_finished()`` / ``close()``."""
        from .. import profiler
        from ..observability import tracer
        self._raise_pending_error()
        t0 = time.perf_counter()
        with tracer.span("ckpt/snapshot", cat="ckpt", args={"step": int(step)}):
            snapshot = capture(step, module=module, trainer=trainer,
                               arg_params=arg_params, aux_params=aux_params,
                               epoch=epoch, nbatch=nbatch,
                               include_rng=include_rng, extra_meta=extra_meta)
        from ..analysis import sanitize
        if "threads" in sanitize.active():
            # ownership transition: the snapshot must be host-landed BEFORE
            # save() returns — the caller's next fused step donates (and on
            # accelerators deletes) the device buffers it would otherwise
            # still reference (the PR 2 race this subsystem closed)
            sanitize.assert_host_landed(
                snapshot.arrays, origin=f"CheckpointManager.save(step={step})")
        job = _SaveJob(snapshot)
        self._ensure_writer()
        self._queue.put(job)
        self._last_step = int(step)
        blocked_ms = (time.perf_counter() - t0) * 1e3
        profiler.record_checkpoint_save(blocked_ms)
        if blocking:
            job.done.wait()
            if job.error is not None:
                with self._lock:    # surfaced here — don't re-raise later
                    if job.error in self._errors:
                        self._errors.remove(job.error)
                raise job.error
        return job

    def _raise_pending_error(self):
        with self._lock:
            if self._errors:
                err = self._errors[0]
                self._errors.clear()
                raise err

    def wait_until_finished(self):
        """Drain the writer queue; re-raise the first writer error."""
        self._queue.join()
        self._raise_pending_error()

    def close(self):
        """Drain pending saves and stop the writer thread."""
        try:
            self.wait_until_finished()
        finally:
            if self._thread is not None and self._thread.is_alive():
                self._queue.put(None)
                self._thread.join(timeout=30)
            self._thread = None

    def _ensure_writer(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._writer_loop,
                                            name="mxtpu-ckpt-writer",
                                            daemon=True)
            self._thread.start()

    def _writer_loop(self):
        from ..resilience import retry_transient
        from ..resilience.watchdog import heartbeat
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            heartbeat("ckpt")
            try:
                # Transient fs errors (ENOSPC races, NFS hiccups, injected
                # io_error faults) are retried — staging dirs are reusable
                # and commit_dir tolerates a torn previous attempt, so
                # _write is idempotent per job. Logic errors (and the test
                # hooks' _Boom) escalate on the first occurrence.
                retry_transient(self._write, job,
                                label=f"ckpt.write[{job.snapshot.step}]")
            except BaseException as e:  # keep the writer alive past one bad job
                job.error = e
                with self._lock:
                    self._errors.append(e)
                self.logger.warning("CheckpointManager: save of step %s "
                                    "failed: %s", job.snapshot.step, e)
            finally:
                job.done.set()
                self._queue.task_done()

    # -- the write (runs on the writer thread) -----------------------------
    def _write(self, job: _SaveJob):
        import jax
        from .. import profiler
        from ..analysis import sanitize
        from ..observability import tracer
        if "threads" in sanitize.active():
            # serialization is owned by the writer thread (blocking saves
            # wait on job.done rather than writing inline)
            sanitize.assert_owner_thread(self._thread,
                                         origin="CheckpointManager._write")
        t0 = time.perf_counter()
        snap = job.snapshot.materialize()   # no-op: capture() landed on host
        step = snap.step
        name = f"{self.step_prefix}-{step}"
        rank = jax.process_index()
        if "before_write" in self._test_hooks:
            self._test_hooks["before_write"]()
        from ..resilience import fault_point
        fault_point("ckpt.write")
        with tracer.span("ckpt/write", cat="ckpt", args={"step": int(step)}):
            if rank == 0:
                # Only the committing rank may sweep: a non-zero rank returns
                # from the barrier before rank 0 has renamed the PREVIOUS
                # step's staging dir, so its sweep could rmtree a dir rank 0
                # is about to os.replace. Rank 0's writer is serial — by the
                # time it starts step N, step N-1 is committed.
                atomic_io.sweep_stale_staging(
                    self.directory, self.step_prefix,
                    keep={name + atomic_io.TMP_SUFFIX})
            stage = atomic_io.staging_dir(self.directory, name)
            self._write_arrays(stage, snap, rank)
        shard_ms = (time.perf_counter() - t0) * 1e3
        self._barrier()                     # every rank's shard is on disk
        if rank == 0:
            fault_point("ckpt.commit")
            with tracer.span("ckpt/commit", cat="ckpt",
                             args={"step": int(step)}):
                with open(os.path.join(stage, _META_FILE), "w") as f:
                    json.dump(snap.meta, f)
                atomic_io.commit_dir(self.directory, name, fsync=self.fsync,
                                     hooks=self._test_hooks)
            self._gc()
            # commit stats only on the rank that committed — other ranks
            # would read dir_bytes of a not-yet-renamed staging dir (0) and
            # inflate the commits counter
            profiler.record_checkpoint_commit(
                (time.perf_counter() - t0) * 1e3,
                (time.perf_counter() - job.t_enqueued) * 1e3,
                atomic_io.dir_bytes(self.step_path(step)))
        else:
            profiler.record_checkpoint_shard_write(shard_ms)

    @staticmethod
    def _write_arrays(stage: str, snap: TrainingSnapshot, rank: int):
        """One npz per process: every array as a raw uint8 buffer plus a
        ``__meta__`` JSON entry with dtype/shape — immune to npz's
        pickle-or-bust handling of extension dtypes (bfloat16)."""
        entries: Dict[str, np.ndarray] = {}
        table: Dict[str, dict] = {}
        for k, a in snap.arrays.items():
            a = np.ascontiguousarray(a)
            table[k] = {"dtype": str(a.dtype), "shape": list(a.shape)}
            entries[k] = a.view(np.uint8).reshape(-1)
        entries[_META_KEY] = np.frombuffer(
            json.dumps(table).encode(), dtype=np.uint8)
        path = os.path.join(stage, _ARRAYS_FILE.format(rank=rank))
        with open(path, "wb") as f:
            np.savez(f, **entries)

    def _gc(self):
        steps = atomic_io.committed_steps(self.directory, self.step_prefix)
        keep = set(steps if self.max_to_keep is None
                   else steps[-self.max_to_keep:])
        if self.keep_period:
            keep.update(s for s in steps if s % self.keep_period == 0)
        for s in steps:
            if s not in keep:
                atomic_io.remove_step(self.directory, self.step_prefix, s)

    # -- restore -----------------------------------------------------------
    def restore(self, step: Optional[int] = None, module=None, trainer=None,
                mesh=None, restore_rng: bool = True,
                allow_missing: bool = False) -> Optional[TrainingSnapshot]:
        """Load a committed step (default: latest) and push it into the given
        module/trainer. Arrays are re-placed with their saved NamedSharding
        specs. Returns the snapshot (``meta`` carries epoch/nbatch/counters),
        or None when nothing is committed."""
        from .. import profiler
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        native = atomic_io.is_committed(self.directory,
                                        f"{self.step_prefix}-{step}")
        if native:
            snap = self._read_step(step)
        elif step in self._legacy_steps():
            snap = self._read_legacy(step)
        else:
            raise FileNotFoundError(
                f"no committed checkpoint for step {step} under "
                f"{self.directory}"
                + (f" or legacy prefix {self.legacy_prefix}"
                   if self.legacy_prefix else ""))
        mesh = mesh if mesh is not None else default_mesh_for(snap)
        if module is not None:
            if trainer is None:
                trainer = getattr(module, "_trainer", None)
            apply_params(snap, module, mesh=mesh, allow_missing=allow_missing)
        if trainer is not None:
            apply_trainer(snap, trainer, mesh=mesh)
            legacy_states = snap.meta.get("legacy_states_file")
            if legacy_states:
                trainer.load_states(legacy_states)
        if restore_rng:
            apply_rng(snap)
        profiler.record_checkpoint_restore()
        self._last_step = int(step)
        return snap

    def _read_step(self, step: int) -> TrainingSnapshot:
        import jax
        path = self.step_path(step)
        with open(os.path.join(path, _META_FILE)) as f:
            meta = json.load(f)
        rank = jax.process_index()
        fname = os.path.join(path, _ARRAYS_FILE.format(rank=rank))
        if not os.path.exists(fname):
            fname = os.path.join(path, _ARRAYS_FILE.format(rank=0))
        arrays: Dict[str, Any] = {}
        with open(fname, "rb") as f:
            with np.load(f, allow_pickle=False) as z:
                table = json.loads(bytes(z[_META_KEY]).decode())
                for k, info in table.items():
                    from .snapshot import _dtype_from_str
                    buf = z[k]
                    arrays[k] = np.frombuffer(
                        buf.tobytes(), dtype=_dtype_from_str(info["dtype"])
                    ).reshape(info["shape"])
        return TrainingSnapshot(arrays, meta)

    def _read_legacy(self, step: int) -> TrainingSnapshot:
        """Compat loader: a reference-layout ``prefix-####.params`` (plus the
        optional ``.states`` Trainer blob) read back as a snapshot."""
        from ..model import load_checkpoint
        _sym, arg, aux = load_checkpoint(self.legacy_prefix, step)
        arrays: Dict[str, Any] = {}
        for k, v in arg.items():
            arrays[f"arg:{k}"] = v.asnumpy()
        for k, v in aux.items():
            arrays[f"aux:{k}"] = v.asnumpy()
        meta = {"format": 0, "step": int(step), "epoch": int(step),
                "nbatch": None, "legacy": True, "shardings": {},
                "trainer": None, "rng": None}
        states = f"{self.legacy_prefix}-{step:04d}.states"
        if os.path.exists(states):
            meta["legacy_states_file"] = states
        return TrainingSnapshot(arrays, meta)

    # -- preemption --------------------------------------------------------
    def install_preemption_handler(self, module=None, trainer=None,
                                   state_fn: Optional[Callable[[], dict]] = None,
                                   signals=(signal.SIGTERM,),
                                   include_sigint: bool = False):
        """Hook SIGTERM (TPU fleet preemption notice) to run ONE final
        blocking save and drain the writer, then hand the signal back: a
        previous Python handler is chained; the default disposition
        (SIG_DFL, i.e. terminate) is restored and the signal re-delivered so
        the preemption notice still kills the job; SIG_IGN stays ignored.
        ``state_fn`` may supply the save kwargs (must include ``step``);
        otherwise the last saved step + 1 is used with the given
        module/trainer — plus the module's live ``_fit_progress``
        epoch/nbatch (maintained by ``Module.fit``) so a mid-epoch
        preemption resumes mid-epoch instead of replaying the epoch.

        ``include_sigint=True`` opts Ctrl-C into the same final-save +
        re-delivery contract (long local runs); default off — an interactive
        Ctrl-C normally wants KeyboardInterrupt semantics, not a save."""
        if self._preempt_installed:
            return
        if include_sigint and signal.SIGINT not in signals:
            signals = tuple(signals) + (signal.SIGINT,)
        prev = {}

        def _handler(signum, frame):
            try:
                from ..observability import flight
                flight.record("sigterm_drain", signum=int(signum))
                flight.dump("sigterm_drain", extra={"signum": int(signum)})
                try:
                    self._raise_pending_error()
                except BaseException as e:
                    # a stale async-writer failure must not abort the final save
                    self.logger.warning("CheckpointManager: pending writer "
                                        "error at preemption: %s", e)
                if state_fn is not None:
                    kwargs = dict(state_fn())
                else:
                    kwargs = {"module": module, "trainer": trainer,
                              "step": (self._last_step or 0) + 1}
                    prog = getattr(module, "_fit_progress", None)
                    if prog:
                        kwargs.setdefault("epoch", prog.get("epoch"))
                        kwargs.setdefault("nbatch", prog.get("nbatch"))
                kwargs["blocking"] = True
                self.logger.warning(
                    "CheckpointManager: signal %s — final blocking save of "
                    "step %s", signum, kwargs.get("step"))
                self.save(**kwargs)
                self.wait_until_finished()
            finally:
                p = prev.get(signum)
                if callable(p):
                    p(signum, frame)
                elif p == signal.SIG_DFL:
                    # the common previous disposition is the default action
                    # (terminate) — restore it and re-deliver so the
                    # preemption notice still kills the job after the save
                    signal.signal(signum, signal.SIG_DFL)
                    os.kill(os.getpid(), signum)
                # SIG_IGN / unknown (None): nothing to chain to

        for sig in signals:
            prev[sig] = signal.signal(sig, _handler)
        self._preempt_installed = True


# ---------------------------------------------------------------------------
# legacy-layout writer (the one path for prefix-####.params)
# ---------------------------------------------------------------------------


def strip_amp_cast(sym_json: str) -> str:
    """Drop ``amp_cast``/``amp_multicast`` nodes from a symbol JSON graph,
    rewiring consumers to the cast's input (reference
    ``Symbol._remove_amp_cast`` semantics). Graphs without amp nodes pass
    through untouched."""
    g = json.loads(sym_json)
    nodes = g.get("nodes")
    if not isinstance(nodes, list) or not any(
            n.get("op") in ("amp_cast", "amp_multicast") for n in nodes):
        return sym_json
    # resolve (node, out_idx) through amp nodes to the real producer
    def resolve(ref):
        nid, out, ver = (ref + [0])[:3] if len(ref) < 3 else ref
        while nodes[nid].get("op") in ("amp_cast", "amp_multicast"):
            nid, out, ver = (nodes[nid]["inputs"][out] + [0])[:3]
        return [nid, out, ver]

    keep = [i for i, n in enumerate(nodes)
            if n.get("op") not in ("amp_cast", "amp_multicast")]
    remap = {old: new for new, old in enumerate(keep)}
    new_nodes = []
    for i in keep:
        n = dict(nodes[i])
        n["inputs"] = [[remap[r[0]], r[1], r[2]]
                       for r in (resolve(ref) for ref in n.get("inputs", []))]
        new_nodes.append(n)
    g["nodes"] = new_nodes
    if "arg_nodes" in g:
        g["arg_nodes"] = [remap[i] for i in g["arg_nodes"] if i in remap]
    if "heads" in g:
        g["heads"] = [[remap[r[0]], r[1], r[2]]
                      for r in (resolve(h) for h in g["heads"])]
    g.pop("node_row_ptr", None)   # stale after renumbering; loaders rebuild it
    return json.dumps(g)


def save_legacy(prefix: str, epoch: int, symbol=None, arg_params=None,
                aux_params=None, remove_amp_cast: bool = True):
    """Atomic writer for the reference checkpoint layout
    (``prefix-symbol.json`` + ``prefix-####.params``). All legacy-surface
    savers (``model.save_checkpoint``, ``FeedForward.save``,
    ``callback.do_checkpoint``) funnel through here, so a kill mid-save can
    no longer tear the artifact."""
    from .. import ndarray as nd
    if symbol is not None:
        if hasattr(symbol, "tojson"):
            sym_json = symbol.tojson()
            if remove_amp_cast:
                sym_json = strip_amp_cast(sym_json)
        else:
            sym_json = json.dumps({"framework": "mxtpu",
                                   "block": type(symbol).__name__,
                                   "repr": repr(symbol)})
        atomic_io.atomic_write_bytes(f"{prefix}-symbol.json",
                                     sym_json.encode())
    payload = {}
    for k, v in (arg_params or {}).items():
        payload[f"arg:{k}"] = v
    for k, v in (aux_params or {}).items():
        payload[f"aux:{k}"] = v
    nd.save(f"{prefix}-{epoch:04d}.params", payload)
