"""Losses — parity with ``python/mxnet/gluon/loss.py`` (11 losses: L2/L1/SigmoidBCE/
SoftmaxCE/KLDiv/CTC/Huber/Hinge/SquaredHinge/Logistic/Triplet + PoissonNLL/Cosine)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from .block import HybridBlock


def _apply_weighting(loss, weight: Optional[float], sample_weight: Optional[NDArray]):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    return label.reshape(pred.shape) if pred.shape != label.shape else label


class Loss(HybridBlock):
    def __init__(self, weight: Optional[float], batch_axis: int = 0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_all_but_batch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return nd.mean(loss, axis=axes) if axes else loss


class L2Loss(Loss):
    def __init__(self, weight: float = 1.0, batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean_all_but_batch(loss)


class L1Loss(Loss):
    def __init__(self, weight: Optional[float] = None, batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional from_sigmoid (loss.py SigmoidBCELoss) — numerically stable
    log-sum-exp form when given logits."""

    def __init__(self, from_sigmoid: bool = False, weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            loss = nd.relu(pred) - pred * label + nd.softrelu(-nd.abs(pred))
        else:
            eps = 1e-12
            loss = -(nd.log(pred + eps) * label + nd.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """loss.py SoftmaxCELoss: sparse or dense labels, optional pre-softmax inputs."""

    def __init__(self, axis: int = -1, sparse_label: bool = True,
                 from_logits: bool = False, weight: Optional[float] = None,
                 batch_axis: int = 0, ignore_label=None, **kwargs):
        """``ignore_label`` (extension beyond the reference gluon loss, matching
        the symbolic ``SoftmaxOutput(use_ignore=True)`` capability): sparse
        label positions equal to it contribute zero loss and zero gradient —
        the masking contract bucketed/padded pipelines need."""
        super().__init__(weight, batch_axis, **kwargs)
        if ignore_label is not None and not sparse_label:
            raise ValueError("ignore_label requires sparse_label=True "
                             "(dense one-hot labels have no ignore id)")
        self._axis = axis
        self._sparse = sparse_label
        self._from_logits = from_logits
        self._ignore_label = ignore_label

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        if self._sparse:
            loss = -nd.pick(pred, label, axis=self._axis, keepdims=False)
            if self._ignore_label is not None:
                loss = loss * (label != float(self._ignore_label))
        else:
            label = _reshape_like(pred, label)
            loss = -nd.sum(pred * label, axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits: bool = True, axis: int = -1,
                 weight: Optional[float] = None, batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        loss = label * (nd.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho: float = 1.0, weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        err = nd.abs(label - pred)
        loss = nd.where(err > self._rho, err - 0.5 * self._rho,
                        0.5 / self._rho * nd.square(err))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class HingeLoss(Loss):
    def __init__(self, margin: float = 1.0, weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.relu(self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin: float = 1.0, weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(nd.relu(self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class LogisticLoss(Loss):
    def __init__(self, label_format: str = "signed", weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._fmt = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._fmt == "binary":
            label = 2 * label - 1
        loss = nd.softrelu(-pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class TripletLoss(Loss):
    def __init__(self, margin: float = 1.0, weight: Optional[float] = None,
                 batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        pos = nd.sum(nd.square(pred - positive), axis=self._batch_axis, exclude=True)
        neg = nd.sum(nd.square(pred - negative), axis=self._batch_axis, exclude=True)
        loss = nd.relu(pos - neg + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, from_logits: bool = True, compute_full: bool = False,
                 weight: Optional[float] = None, batch_axis: int = 0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._from_logits:
            loss = nd.exp(pred) - label * pred
        else:
            loss = pred - label * nd.log(pred + 1e-8)
        if self._compute_full:
            stirling = (label * nd.log(label + 1e-12) - label
                        + 0.5 * nd.log(2 * 3.14159265 * (label + 1e-12)))
            loss = loss + nd.where(label > 1, stirling, nd.zeros_like(label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return nd.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight: Optional[float] = None, batch_axis: int = 0,
                 margin: float = 0.0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        num = nd.sum(input1 * input2, axis=-1)
        den = nd.sqrt(nd.sum(nd.square(input1), axis=-1)
                      * nd.sum(nd.square(input2), axis=-1) + 1e-12)
        cos = num / den
        pos = 1 - cos
        neg = nd.relu(cos - self._margin)
        loss = nd.where(label == 1, pos, neg)
        return _apply_weighting(loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """Connectionist temporal classification (loss.py CTCLoss → contrib.ctc_loss op).

    Layout follows the reference default NTC; labels (N, L) with 0 reserved for blank.
    """

    def __init__(self, layout: str = "NTC", label_layout: str = "NT",
                 weight: Optional[float] = None, **kwargs):
        super().__init__(weight, batch_axis=0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)  # -> (T, N, C)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)  # -> (N, L)
        T, N = pred.shape[0], pred.shape[1]
        if label_lengths is None:
            lab = label.data.astype(jnp.int32)
            label_lengths = NDArray(jnp.sum(lab > 0, axis=1).astype(jnp.int32))
        if pred_lengths is None:
            pred_lengths = NDArray(jnp.full((N,), T, jnp.int32))
        loss = nd.contrib.ctc_loss(pred, label, pred_lengths, label_lengths)
        return _apply_weighting(loss, self._weight, sample_weight)
