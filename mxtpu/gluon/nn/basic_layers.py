"""Basic layers — parity with ``python/mxnet/gluon/nn/basic_layers.py``:
Sequential/HybridSequential, Dense, Activation, Dropout, BatchNorm, LayerNorm,
InstanceNorm, Embedding, Flatten, Lambda/HybridLambda.
"""

from __future__ import annotations

from typing import Callable, Optional

from ... import autograd
from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    """Stack of blocks run in order (dynamic)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        return list(self._children.values())[key]

    def __len__(self):
        return len(self._children)


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        return list(self._children.values())[key]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer (basic_layers.py Dense → FullyConnected op)."""

    def __init__(self, units: int, activation: Optional[str] = None,
                 use_bias: bool = True, flatten: bool = True, dtype="float32",
                 weight_initializer=None, bias_initializer="zeros",
                 in_units: int = 0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._act = activation
        self._use_bias = use_bias
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          dtype=dtype, init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,), dtype=dtype,
                                            init=bias_initializer,
                                            allow_deferred_init=True)

    def forward(self, x):
        if self.weight._data is None:
            in_units = 1
            if self._flatten:
                for s in x.shape[1:]:
                    in_units *= s
            else:
                in_units = x.shape[-1]
            self.weight._finish_deferred_init((self._units, in_units))
        if self._use_bias and self.bias._data is None:
            self.bias._finish_deferred_init((self._units,))
        out = nd.FullyConnected(x, self.weight.data(),
                                self.bias.data() if self._use_bias else None,
                                num_hidden=self._units, no_bias=not self._use_bias,
                                flatten=self._flatten)
        if self._act:
            out = nd.Activation(out, act_type=self._act)
        return out


class Activation(HybridBlock):
    def __init__(self, activation: str, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._act = activation

    def forward(self, x):
        return nd.Activation(x, act_type=self._act)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha: float = 0.01, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def forward(self, x):
        return nd.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(0,),
                                         init=alpha_initializer or initializer.Constant(0.25),
                                         allow_deferred_init=True)

    def forward(self, x):
        if self.alpha._data is None:
            self.alpha._finish_deferred_init((x.shape[1] if x.ndim > 1 else 1,))
        return nd.LeakyReLU(x, self.alpha.data(), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha: float = 1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def forward(self, x):
        return nd.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return nd.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def forward(self, x):
        return nd.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta: float = 1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def forward(self, x):
        return x * nd.sigmoid(self._beta * x)


class Dropout(HybridBlock):
    def __init__(self, rate: float, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return nd.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def forward(self, x):
        return nd.flatten(x)


class Lambda(Block):
    def __init__(self, function: Callable, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._fn = function if callable(function) else getattr(nd, function)

    def forward(self, *args):
        return self._fn(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function: Callable, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._fn = function if callable(function) else getattr(nd, function)

    def forward(self, *args):
        return self._fn(*args)


class Embedding(HybridBlock):
    """Embedding lookup; ``sparse_grad=True`` records a row-sparse weight gradient
    (gluon Embedding sparse_grad parity → lazy optimizer updates touch only the
    batch's rows; see ndarray/sparse.py). The sparse path is imperative-only — a
    hybridized block traces with the tape paused and falls back to dense grads."""

    def __init__(self, input_dim: int, output_dim: int, dtype="float32",
                 weight_initializer=None, sparse_grad: bool = False,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim, self._output_dim = input_dim, output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                          dtype=dtype, init=weight_initializer,
                                          grad_stype="row_sparse" if sparse_grad
                                          else "default")

    def forward(self, x):
        from ... import autograd
        if not (self._sparse_grad and autograd.is_recording()):
            return nd.Embedding(x, self.weight.data(), input_dim=self._input_dim,
                                output_dim=self._output_dim)
        import jax.numpy as jnp
        from ...ndarray.ndarray import NDArray
        from ...ndarray.sparse import RawRowSparse
        w = self.weight.data()
        ids = x.data.astype(jnp.int32)
        out = NDArray(w.data[ids])
        wshape, outdim = w.shape, self._output_dim

        def backward_fn(saved, out_grads):
            (g,) = out_grads
            flat_ids = saved["ids"].reshape(-1)
            flat_g = g.reshape(-1, outdim)
            return [None, RawRowSparse(flat_ids, flat_g, wshape)]

        autograd.record_custom_node(None, [x, w], [out], backward_fn=backward_fn,
                                    saved={"ids": ids, "outs": [out.data]})
        return out


class BatchNorm(HybridBlock):
    """BatchNorm layer (basic_layers.py BatchNorm).

    Training uses batch stats and updates the running aux stats in place — the handle
    mutation is captured by the CachedOp trace as a state output (jit.py), replacing
    the reference's in-op aux-state writes (batch_norm.cc).
    """

    def __init__(self, axis: int = 1, momentum: float = 0.9, epsilon: float = 1e-5,
                 center: bool = True, scale: bool = True, use_global_stats: bool = False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros", running_variance_initializer="ones",
                 in_channels: int = 0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis, self._momentum, self._eps = axis, momentum, epsilon
        self._center, self._scale = center, scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True,
                                         differentiable=scale)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True,
                                        differentiable=center)
            self.running_mean = self.params.get("running_mean", shape=(in_channels,),
                                                init=running_mean_initializer,
                                                allow_deferred_init=True,
                                                differentiable=False)
            self.running_var = self.params.get("running_var", shape=(in_channels,),
                                               init=running_variance_initializer,
                                               allow_deferred_init=True,
                                               differentiable=False)

    def _finish(self, c):
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p._data is None:
                p._finish_deferred_init((c,))

    def forward(self, x):
        self._finish(x.shape[self._axis])
        gamma, beta = self.gamma.data(), self.beta.data()
        rmean, rvar = self.running_mean.data(), self.running_var.data()
        if autograd.is_training() and not self._use_global_stats:
            out, bmean, bvar = nd.batch_norm_train(
                x, gamma, beta, eps=self._eps, fix_gamma=not self._scale,
                axis=self._axis)
            m = self._momentum
            rmean._set_data((m * rmean.data + (1 - m) * bmean.data))
            rvar._set_data((m * rvar.data + (1 - m) * bvar.data))
            return out
        return nd.BatchNorm(x, gamma, beta, rmean, rvar, eps=self._eps,
                            fix_gamma=not self._scale, use_global_stats=True,
                            axis=self._axis)


class LayerNorm(HybridBlock):
    def __init__(self, axis: int = -1, epsilon: float = 1e-5, center: bool = True,
                 scale: bool = True, beta_initializer="zeros",
                 gamma_initializer="ones", in_channels: int = 0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis, self._eps = axis, epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer, allow_deferred_init=True,
                                         differentiable=scale)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer, allow_deferred_init=True,
                                        differentiable=center)

    def forward(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._finish_deferred_init((c,))
        return nd.LayerNorm(x, self.gamma.data(), self.beta.data(), axis=self._axis,
                            eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis: int = 1, epsilon: float = 1e-5, center: bool = True,
                 scale: bool = False, beta_initializer="zeros",
                 gamma_initializer="ones", in_channels: int = 0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer, allow_deferred_init=True,
                                         differentiable=scale)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer, allow_deferred_init=True,
                                        differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p._data is None:
                p._finish_deferred_init((c,))
        return nd.InstanceNorm(x, self.gamma.data(), self.beta.data(), eps=self._eps)
