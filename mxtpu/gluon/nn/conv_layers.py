"""Convolution / pooling layers — parity with ``python/mxnet/gluon/nn/conv_layers.py``:
Conv1D/2D/3D, Conv1D/2D/3DTranspose, Max/Avg pooling (1/2/3D), GlobalMax/GlobalAvg,
ReflectionPad2D.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ... import ndarray as nd
from ..block import HybridBlock


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels: int, kernel_size, strides, padding, dilation,
                 groups: int, layout: str, in_channels: int = 0,
                 activation: Optional[str] = None, use_bias: bool = True,
                 weight_initializer=None, bias_initializer="zeros",
                 ndim: int = 2, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._kernel = _pair(kernel_size, ndim)
        self._strides = _pair(strides, ndim)
        self._padding = _pair(padding, ndim)
        self._dilation = _pair(dilation, ndim)
        self._groups = groups
        self._act = activation
        self._use_bias = use_bias
        self._ndim = ndim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels // groups if in_channels else 0)
                + self._kernel, init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)

    def _finish(self, x):
        if self.weight._data is None:
            cin = x.shape[1]
            self.weight._finish_deferred_init(
                (self._channels, cin // self._groups) + self._kernel)

    def forward(self, x):
        self._finish(x)
        out = nd.Convolution(
            x, self.weight.data(), self.bias.data() if self._use_bias else None,
            kernel=self._kernel, stride=self._strides, dilate=self._dilation,
            pad=self._padding, num_filter=self._channels, num_group=self._groups,
            no_bias=not self._use_bias)
        if self._act:
            out = nd.Activation(out, act_type=self._act)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=1, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=2, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, ndim=3, **kwargs)


class _ConvTranspose(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, output_padding,
                 dilation, groups, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", ndim=2,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._kernel = _pair(kernel_size, ndim)
        self._strides = _pair(strides, ndim)
        self._padding = _pair(padding, ndim)
        self._out_pad = _pair(output_padding, ndim)
        self._dilation = _pair(dilation, ndim)
        self._groups = groups
        self._act = activation
        self._use_bias = use_bias
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(in_channels, channels // groups if channels else 0)
                + self._kernel, init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer)

    def forward(self, x):
        if self.weight._data is None:
            cin = x.shape[1]
            self.weight._finish_deferred_init(
                (cin, self._channels // self._groups) + self._kernel)
        out = nd.Deconvolution(
            x, self.weight.data(), self.bias.data() if self._use_bias else None,
            kernel=self._kernel, stride=self._strides, pad=self._padding,
            adj=self._out_pad, dilate=self._dilation, num_filter=self._channels,
            num_group=self._groups, no_bias=not self._use_bias)
        if self._act:
            out = nd.Activation(out, act_type=self._act)
        return out


class Conv1DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, output_padding,
                         dilation, groups, ndim=1, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, output_padding,
                         dilation, groups, ndim=2, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, output_padding,
                         dilation, groups, ndim=3, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, pool_type: str, ndim: int,
                 ceil_mode: bool = False, global_pool: bool = False,
                 count_include_pad: bool = True, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kernel = _pair(pool_size, ndim)
        self._strides = _pair(strides if strides is not None else pool_size, ndim)
        self._padding = _pair(padding, ndim)
        self._pool_type = pool_type
        self._global = global_pool
        self._ceil = ceil_mode
        self._cip = count_include_pad

    def forward(self, x):
        return nd.Pooling(x, kernel=self._kernel, pool_type=self._pool_type,
                          global_pool=self._global, stride=self._strides,
                          pad=self._padding,
                          pooling_convention="full" if self._ceil else "valid",
                          count_include_pad=self._cip)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, "max", 1, ceil_mode, **kw)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, "max", 2, ceil_mode, **kw)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, ceil_mode=False, **kw):
        super().__init__(pool_size, strides, padding, "max", 3, ceil_mode, **kw)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, "avg", 1, ceil_mode,
                         count_include_pad=count_include_pad, **kw)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, "avg", 2, ceil_mode,
                         count_include_pad=count_include_pad, **kw)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, ceil_mode=False,
                 count_include_pad=True, **kw):
        super().__init__(pool_size, strides, padding, "avg", 3, ceil_mode,
                         count_include_pad=count_include_pad, **kw)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "max", 1, global_pool=True, **kw)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "max", 2, global_pool=True, **kw)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "max", 3, global_pool=True, **kw)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "avg", 1, global_pool=True, **kw)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "avg", 2, global_pool=True, **kw)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, **kw):
        super().__init__(1, 1, 0, "avg", 3, global_pool=True, **kw)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._padding = _pair(padding, 4) if not isinstance(padding, int) else (
            padding,) * 4

    def forward(self, x):
        p = self._padding
        return nd.pad(x, mode="reflect",
                      pad_width=(0, 0, 0, 0, p[0], p[1], p[2], p[3]))
