"""Block / HybridBlock — parity with ``python/mxnet/gluon/block.py``.

* ``Block`` (block.py:126): dynamic imperative module with auto-registered children
  and parameters, name scoping, ``collect_params``, ``save/load_parameters``.
* ``HybridBlock`` (block.py:536): callable both imperatively and compiled.
  ``hybridize()`` in the reference traces ``hybrid_forward`` with symbol proxies into
  a ``CachedOp`` (block.py:746 ``_build_cache``); here the SAME python forward is traced
  by ``jax.jit`` through ``mxtpu.jit.CachedOp`` — no symbol language needed, and the
  trace recompiles automatically per input signature (shape bucketing).
* ``export`` writes params + StableHLO text (≈ symbol JSON + params, block.py:866).

``hybrid_forward(F, x, ...)`` is supported for reference-style subclasses (``F`` is
``mxtpu.nd``); idiomatic subclasses may instead override ``forward(x)`` directly and
read ``self.<param>.data()``.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import ndarray as nd_mod
from ..jit import CachedOp, export_stablehlo
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

_name_counter = threading.local()


class _BlockScope:
    """Hierarchical name manager (block.py _BlockScope parity)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter: Dict[str, int] = {}
        self._old = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                if not hasattr(_name_counter, "counts"):
                    _name_counter.counts = {}
                cnt = _name_counter.counts.get(hint, 0)
                _name_counter.counts[hint] = cnt + 1
                prefix = f"{hint}{cnt}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, shared=params)
            return prefix, params
        if prefix is None:
            cnt = current._counter.get(hint, 0)
            current._counter[hint] = cnt + 1
            prefix = f"{hint}{cnt}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, shared=None)
        else:
            params = ParameterDict(params.prefix, shared=params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        self._old = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        _BlockScope._current.value = self._old
        return False


class Block:
    """Base neural-network module (gluon.Block parity)."""

    def __init__(self, prefix: Optional[str] = None, params: Optional[ParameterDict] = None):
        hint = re.sub(r"(?<!^)(?=[A-Z])", "", type(self).__name__).lower()
        self._prefix, self._params = _BlockScope.create(prefix, params, hint)
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._forward_hooks: List[Callable] = []
        self._forward_pre_hooks: List[Callable] = []

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            params = self.__dict__.get("_params")
            if params is not None:
                params._params[value.name] = value
        super().__setattr__(name, value)

    def register_child(self, block: "Block", name: Optional[str] = None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    # -- properties --------------------------------------------------------
    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._name

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self) -> _BlockScope:
        return self._scope

    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pat = re.compile(select)
            for name, p in self._params.items():
                if pat.match(name):
                    ret._params[name] = p
        for child in self._children.values():
            sub = child.collect_params(select)
            for name, p in sub.items():
                ret._params[name] = p
        return ret

    # -- lifecycle ---------------------------------------------------------
    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False):
        self.collect_params().initialize(init=init, ctx=ctx, verbose=verbose,
                                         force_reinit=force_reinit)
        return self

    def cast(self, dtype):
        for p in self.collect_params().values():
            p.cast(dtype)
        return self

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- serialization -----------------------------------------------------
    def save_parameters(self, filename: str):
        """block.py:313 save_parameters — strips the block prefix like the reference."""
        params = self.collect_params()
        arrays = {}
        for name, p in params.items():
            if p._data is None:
                continue
            key = name[len(self.prefix):] if name.startswith(self.prefix) else name
            arrays[key] = p.data()
        nd_mod.save(filename, arrays)

    def load_parameters(self, filename: str, ctx=None, allow_missing: bool = False,
                        ignore_extra: bool = False):
        loaded = nd_mod.load(filename)
        params = self.collect_params()
        restored = {}
        for k, v in loaded.items():
            full = k if k in params else self.prefix + k
            restored[full] = v
        if not allow_missing:
            for name, p in params.items():
                if name not in restored:
                    raise ValueError(f"parameter {name} missing from {filename}")
        for name, arr in restored.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise ValueError(f"parameter {name} from file not found in block")
            p = params[name]
            if p.shape is not None:
                # declared dims must match the file (0 = deferred, adopts file dim)
                if len(p.shape) != arr.ndim or any(
                        s > 0 and s != f for s, f in zip(p.shape, arr.shape)):
                    raise ValueError(
                        f"parameter {name}: declared shape {p.shape} incompatible "
                        f"with loaded shape {arr.shape}")
            if p._data is None:
                from .. import initializer
                p.shape = tuple(arr.shape)
                p._init_impl(p.init or initializer.Zero(), None)
            p.set_data(arr)

    # legacy-name parity (block.py save_params/load_params deprecated aliases)
    save_params = save_parameters
    load_params = load_parameters

    # -- execution ---------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active: bool = True, **kwargs):
        """No-op on plain Blocks except recursing into children (reference parity)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        out = self(*inputs)
        n_params = sum(int(np_prod(p.shape)) for p in self.collect_params().values()
                       if p.shape)
        print(f"{type(self).__name__}: params={n_params}")
        return out

    def __repr__(self):
        lines = [f"{type(self).__name__}("]
        for name, child in self._children.items():
            lines.append(f"  ({name}): {type(child).__name__}")
        lines.append(")")
        return "\n".join(lines)


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


class HybridBlock(Block):
    """Block that can run compiled (gluon.HybridBlock parity; jit.CachedOp backend)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op: Optional[CachedOp] = None
        self._flags: Dict[str, Any] = {}

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, **kwargs):
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape,
                           **kwargs)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _ensure_params_ready(self, args):
        """Finish deferred shape inference by one imperative dry-run if needed."""
        params = self.collect_params()
        if any(p._data is None for p in params.values()):
            # run imperatively once: layers complete their own deferred params
            self.forward(*args)

    def __call__(self, *args, **kwargs):
        if self._active and kwargs:
            # keyword/optional-arg calls fall back to the imperative path (the
            # CachedOp trace covers the positional signature)
            return super().__call__(*args, **kwargs)
        if self._active:
            args = [a if isinstance(a, NDArray) else nd_mod.array(a) for a in args]
            if self._cached_op is None:
                self._ensure_params_ready(args)
                params = [p.data() for p in self.collect_params().values()
                          if p._data is not None]
                self._cached_op = CachedOp(
                    lambda *xs: self.forward(*xs), params=params,
                    static_alloc=self._flags.get("static_alloc", False),
                    static_shape=self._flags.get("static_shape", False))
            return self._cached_op(*args)
        return super().__call__(*args, **kwargs)

    def forward(self, *args):
        """Default: dispatch to reference-style ``hybrid_forward(F, x, **params)``."""
        if hasattr(self, "hybrid_forward"):
            params = {}
            for name, p in self._params.items():
                short = name[len(self.prefix):] if name.startswith(self.prefix) else name
                try:
                    params[short] = p.data()
                except Exception:
                    p._finish_deferred_init(self._infer_param_shape(short, p, args))
                    params[short] = p.data()
            return self.hybrid_forward(nd_mod, *args, **params)
        raise NotImplementedError(
            f"{type(self).__name__} must implement forward or hybrid_forward")

    def _infer_param_shape(self, short_name, param, args):
        raise NotImplementedError(
            f"cannot infer deferred shape for {param.name}; initialize with a "
            "complete shape or implement shape inference in the layer")

    def export(self, path: str, epoch: int = 0):
        """StableHLO + params export (≈ block.py:866 export to symbol-json+params):
        writes ``path-####.params`` and ``path-symbol.stablehlo.txt`` (real StableHLO
        of the first traced signature)."""
        if self._cached_op is None or not self._cached_op._cache:
            raise RuntimeError("export requires a hybridized block that has run once")
        self.save_parameters(f"{path}-{epoch:04d}.params")
        import jax.numpy as jnp
        from ..base import dtype_np
        sig = next(iter(self._cached_op._cache))
        arg_shapes = sig[0]  # ((shape, dtype, sharding), ...) per input
        examples = [NDArray(jnp.zeros(s, dtype_np(dt)))
                    for s, dt, *_rest in arg_shapes]
        from .. import autograd as _ag
        with _ag.predict_mode():
            text = export_stablehlo(lambda *xs: self.forward(*xs), examples)
        with open(f"{path}-symbol.stablehlo.txt", "w") as f:
            f.write(text)
        return path

    def infer_shape(self, *args):
        self._ensure_params_ready([a if isinstance(a, NDArray) else nd_mod.array(a)
                                   for a in args])


class SymbolBlock(HybridBlock):
    """Gluon block over a Symbol graph (block.py:950 SymbolBlock parity).

    ``outputs`` is a Symbol (or list → Group); ``inputs`` names the free variables
    fed by ``forward(*args)``; every other argument becomes a Parameter (exact
    symbol name, deferred shape completed by ``infer_shape`` at first forward).
    Forward evaluates the DAG on raw arrays and records ONE tape node whose replay
    closure reuses the forward's resolved RNG/flag state — the same single-node
    contract the CachedOp path uses (autograd.record_custom_node).
    """

    def __init__(self, outputs, inputs, params=None, prefix=None):
        super().__init__(prefix=prefix)
        from ..symbol import Group, Symbol
        from ..symbol.symbol import _AUX_PARAMS  # noqa: F401 (doc pointer)
        if isinstance(outputs, (list, tuple)):
            outputs = Group(list(outputs))
        self._sym = outputs
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._input_names = [i if isinstance(i, str) else i.name for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        self._sym_param_names = [n for n in arg_names
                                 if n not in self._input_names] + aux_names
        given = dict(params.items()) if params is not None else {}
        for n in self._sym_param_names:
            if n in given:
                self._params._params[n] = given[n]
            else:
                self._params._params[n] = Parameter(
                    n, shape=None, allow_deferred_init=True,
                    grad_req="null" if n in aux_names else "write")
        self._shapes_done = False

    @staticmethod
    def imports(symbol_file: str, input_names, param_file: Optional[str] = None,
                ctx=None):
        """Load an exported (symbol-json, params) pair (SymbolBlock.imports parity)."""
        from .. import symbol as sym_mod
        from .. import ndarray as nd_mod
        net = SymbolBlock(sym_mod.load(symbol_file), input_names)
        if param_file is not None:
            loaded = nd_mod.load(param_file)
            for name, arr in loaded.items():
                short = name.split(":", 1)[1] if ":" in name else name
                if short in net._params._params:
                    p = net._params._params[short]
                    p.shape = tuple(arr.shape)
                    p._init_impl(p.init or "zeros", None)
                    p.set_data(arr)
        return net

    def _complete_shapes(self, args):
        from ..symbol.symbol import _req_of  # noqa: F401
        shapes = {n: tuple(a.shape) for n, a in zip(self._input_names, args)}
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**shapes)
        arg_names = self._sym.list_arguments()
        aux_names = self._sym.list_auxiliary_states()
        for n, s in list(zip(arg_names, arg_shapes)) + \
                list(zip(aux_names, aux_shapes)):
            if n in self._params._params and s is not None:
                p = self._params._params[n]
                if p._data is None:
                    p._finish_deferred_init(s)
                    if p._data is None:  # initialize() never called on the block
                        p.shape = tuple(s)
                        p.initialize()
        self._shapes_done = True

    def forward(self, *args):
        from .. import autograd
        from ..symbol.symbol import eval_graph
        if not self._shapes_done:
            self._complete_shapes(args)
        param_handles = [self._params._params[n].data()
                         for n in self._sym_param_names]
        names = self._input_names + self._sym_param_names
        feed = {n: a.data for n, a in
                zip(names, list(args) + param_handles)}
        resolved: dict = {}
        aux_updates: dict = {}
        is_train = autograd.is_training()
        with autograd.pause(train_mode=is_train):
            outs_raw = eval_graph(self._sym._heads, feed, is_train,
                                  aux_updates=aux_updates, resolved=resolved)
        outs = [NDArray(o) for o in outs_raw]
        if autograd.is_recording():
            heads = self._sym._heads

            def pure_fn(*raws):
                feed2 = dict(zip(names, raws))
                res = eval_graph(heads, feed2, is_train, resolved=resolved)
                return tuple(res) if len(res) > 1 else res[0]

            autograd.record_custom_node(pure_fn, list(args) + param_handles, outs)
        for name, new in aux_updates.items():
            if name in self._params._params:
                self._params._params[name].data()._set_data(new)
        return outs[0] if len(outs) == 1 else tuple(outs)
