"""Model zoo (parity with python/mxnet/gluon/model_zoo)."""

from . import model_store, vision
from .vision import get_model
