"""Model zoo (parity with python/mxnet/gluon/model_zoo)."""

from . import model_store, transformer, vision
from .transformer import TransformerLM, transformer_lm
from .vision import get_model
