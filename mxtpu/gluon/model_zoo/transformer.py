"""Decoder-only transformer language model — the TPU-native flagship training
workload.

The reference's transformer support is a single helper op
(``_contrib_div_sqrt_dim``, src/operator/contrib/transformer.cc:33) plus the
gluon-nlp ecosystem it fed; a TPU-first framework makes the transformer a
first-class model-zoo family instead, built over the Pallas flash-attention
kernel (ops/attention.py) per the long-context mandate (SURVEY.md §5).

Architecture (GPT-2-style, pre-LN):

    tokens → embed + learned pos-embed
           → N × [LN → causal MHA → +res, LN → FFN(4d, GELU) → +res]
           → LN → logits = h · Eᵀ   (tied softmax head)

The tied head reuses the token-embedding matrix (Press & Wolf 2017 weight
tying) — one fewer V×d parameter and the standard LM configuration.

Every layer is jit-friendly: static shapes, no data-dependent control flow,
registered nd ops throughout so the imperative autograd tape records the same
graph ``DataParallelTrainer`` traces under jit.
"""

from __future__ import annotations

import math

from ... import ndarray as nd
from ..block import HybridBlock
from ..contrib.nn import MultiHeadAttention
from ..nn.basic_layers import Dense, Embedding, LayerNorm

__all__ = ["TransformerBlock", "TransformerLM", "transformer_lm"]


class TransformerBlock(HybridBlock):
    """One pre-LN decoder block: causal flash MHA + position-wise FFN."""

    def __init__(self, units: int, num_heads: int, ffn_units: int = 0,
                 dropout: float = 0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        ffn_units = ffn_units or 4 * units
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, causal=True,
                                           dropout=dropout)
            self.ln2 = LayerNorm(in_channels=units)
            self.ffn1 = Dense(ffn_units, flatten=False, in_units=units)
            self.ffn2 = Dense(units, flatten=False, in_units=ffn_units)

    def forward(self, x):
        h = x + self.attn(self.ln1(x))
        g = nd.LeakyReLU(self.ffn1(self.ln2(h)), act_type="gelu")
        return h + self.ffn2(g)


class TransformerLM(HybridBlock):
    """Decoder-only LM over token ids.

    Input ``(B, T)`` int tokens, output ``(B, T, vocab)`` logits. ``T`` may be
    anything ≤ ``max_len`` (the learned position table is sliced); multiples
    of 128 engage the Pallas flash kernel on TPU, others fall back to the XLA
    attention reference (ops/attention.py ``_use_pallas``).
    """

    def __init__(self, vocab_size: int, units: int = 512, num_layers: int = 6,
                 num_heads: int = 8, max_len: int = 2048, ffn_units: int = 0,
                 dropout: float = 0.0, tie_weights: bool = True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._vocab = vocab_size
        self._units = units
        self._max_len = max_len
        self._tie = tie_weights
        with self.name_scope():
            self.embedding = Embedding(vocab_size, units,
                                       weight_initializer="normal")
            self.pos_embed = self.params.get(
                "pos_embed", shape=(max_len, units), init="normal")
            self.blocks = []
            for i in range(num_layers):
                blk = TransformerBlock(units, num_heads, ffn_units, dropout)
                setattr(self, f"block{i}", blk)   # registers child + params
                self.blocks.append(blk)
            self.ln_f = LayerNorm(in_channels=units)
            if not tie_weights:
                self.head = Dense(vocab_size, flatten=False, in_units=units)

    def forward(self, tokens):
        B, T = tokens.shape
        if T > self._max_len:
            raise ValueError(f"sequence length {T} exceeds max_len "
                             f"{self._max_len}")
        h = self.embedding(tokens)
        pos = nd.slice_axis(self.pos_embed.data(), axis=0, begin=0, end=T)
        h = h + nd.reshape(pos, (1, T, self._units))
        for blk in self.blocks:
            h = blk(h)
        h = self.ln_f(h)
        if not self._tie:
            return self.head(h)
        # tied softmax head: logits = h · Eᵀ over the embedding table
        w = self.embedding.weight.data()
        flat = nd.reshape(h, (B * T, self._units))
        return nd.reshape(nd.dot(flat, w, transpose_b=True),
                          (B, T, self._vocab))


_PRESETS = {
    # name: (units, layers, heads, max_len)
    "tiny": (64, 2, 2, 256),            # tests
    "small": (512, 6, 8, 1024),         # ~35M params at 16k vocab
    "base": (768, 12, 12, 1024),        # GPT-2 124M-class
    "flagship": (1024, 8, 16, 2048),    # the bench workload: MXU-dominated
    "wide": (2048, 4, 16, 2048),        # fewer/wider blocks: 2048x8192 FFN
                                        # matmuls saturate the MXU (64.9% MFU
                                        # measured on v5e vs 44% at d1024 L8)
}


def transformer_lm(preset: str = "small", vocab_size: int = 16384, **kwargs):
    """Factory over the preset table (model-zoo surface parity with
    ``vision.get_model``)."""
    try:
        units, layers, heads, max_len = _PRESETS[preset]
    except KeyError:
        raise ValueError(
            f"unknown preset {preset!r}; choose from {sorted(_PRESETS)}")
    cfg = dict(units=units, num_layers=layers, num_heads=heads,
               max_len=max_len)
    cfg.update(kwargs)
    return TransformerLM(vocab_size, **cfg)
