"""Decoder-only transformer language model — the TPU-native flagship training
workload.

The reference's transformer support is a single helper op
(``_contrib_div_sqrt_dim``, src/operator/contrib/transformer.cc:33) plus the
gluon-nlp ecosystem it fed; a TPU-first framework makes the transformer a
first-class model-zoo family instead, built over the Pallas flash-attention
kernel (ops/attention.py) per the long-context mandate (SURVEY.md §5).

Architecture (GPT-2-style, pre-LN):

    tokens → embed + learned pos-embed
           → N × [LN → causal MHA → +res, LN → FFN(4d, GELU) → +res]
           → LN → logits = h · Eᵀ   (tied softmax head)

The tied head reuses the token-embedding matrix (Press & Wolf 2017 weight
tying) — one fewer V×d parameter and the standard LM configuration.

Every layer is jit-friendly: static shapes, no data-dependent control flow,
registered nd ops throughout so the imperative autograd tape records the same
graph ``DataParallelTrainer`` traces under jit.
"""

from __future__ import annotations

import math

from ... import ndarray as nd
from ..block import HybridBlock
from ..contrib.nn import MultiHeadAttention, _layout_constrain
from ..nn.basic_layers import Dense, Embedding, LayerNorm

__all__ = ["TransformerBlock", "TransformerLM", "transformer_lm"]


def _constrain_raw(x, entry: str):
    """Raw-jnp twin of ``_layout_constrain`` for the serving step functions
    (identity outside ``parallel.fsdp.layout_scope`` — the sharded serving
    engine opens the scope around every program trace)."""
    from ...parallel import fsdp as _fsdp
    return _fsdp.constrain(x, entry)


class TransformerBlock(HybridBlock):
    """One pre-LN decoder block: causal flash MHA + position-wise FFN."""

    def __init__(self, units: int, num_heads: int, ffn_units: int = 0,
                 dropout: float = 0.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        ffn_units = ffn_units or 4 * units
        with self.name_scope():
            self.ln1 = LayerNorm(in_channels=units)
            self.attn = MultiHeadAttention(units, num_heads, causal=True,
                                           dropout=dropout)
            self.ln2 = LayerNorm(in_channels=units)
            self.ffn1 = Dense(ffn_units, flatten=False, in_units=units)
            self.ffn2 = Dense(units, flatten=False, in_units=ffn_units)

    def forward(self, x):
        h = x + self.attn(self.ln1(x))
        g = nd.LeakyReLU(self.ffn1(self.ln2(h)), act_type="gelu")
        return h + self.ffn2(g)


class TransformerLM(HybridBlock):
    """Decoder-only LM over token ids.

    Input ``(B, T)`` int tokens, output ``(B, T, vocab)`` logits. ``T`` may be
    anything ≤ ``max_len`` (the learned position table is sliced); multiples
    of 128 engage the Pallas flash kernel on TPU, others fall back to the XLA
    attention reference (ops/attention.py ``_use_pallas``).
    """

    def __init__(self, vocab_size: int, units: int = 512, num_layers: int = 6,
                 num_heads: int = 8, max_len: int = 2048, ffn_units: int = 0,
                 dropout: float = 0.0, tie_weights: bool = True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._vocab = vocab_size
        self._units = units
        self._max_len = max_len
        self._tie = tie_weights
        with self.name_scope():
            self.embedding = Embedding(vocab_size, units,
                                       weight_initializer="normal")
            self.pos_embed = self.params.get(
                "pos_embed", shape=(max_len, units), init="normal")
            self.blocks = []
            for i in range(num_layers):
                blk = TransformerBlock(units, num_heads, ffn_units, dropout)
                setattr(self, f"block{i}", blk)   # registers child + params
                self.blocks.append(blk)
            self.ln_f = LayerNorm(in_channels=units)
            if not tie_weights:
                self.head = Dense(vocab_size, flatten=False, in_units=units)

    def forward(self, tokens):
        B, T = tokens.shape
        if T > self._max_len:
            raise ValueError(f"sequence length {T} exceeds max_len "
                             f"{self._max_len}")
        h = self.embedding(tokens)
        pos = nd.slice_axis(self.pos_embed.data(), axis=0, begin=0, end=T)
        h = h + nd.reshape(pos, (1, T, self._units))
        # composed-flagship layout: activations ride the SpecLayout table
        # (sequence-sharded through the block stack under a layout_scope,
        # identity otherwise)
        h = _layout_constrain(h, "seq_activations")
        for blk in self.blocks:
            h = _layout_constrain(blk(h), "seq_activations")
        h = self.ln_f(h)
        if not self._tie:
            return self.head(h)
        # tied softmax head: logits = h · Eᵀ over the embedding table
        w = self.embedding.weight.data()
        flat = nd.reshape(h, (B * T, self._units))
        return nd.reshape(nd.dot(flat, w, transpose_b=True),
                          (B, T, self._vocab))

    # -- autoregressive decoding (TPU-first: one jitted scan, static KV
    # cache — no per-token dispatch, no dynamic shapes) ---------------------
    def _gen_params(self):
        """Raw weight pytree, passed as a jit ARGUMENT so weight updates
        don't recompile the decode program."""
        def raw(p):
            return p.data().data
        layers = []
        for blk in self.blocks:
            at = blk.attn
            layers.append(dict(
                ln1_g=raw(blk.ln1.gamma), ln1_b=raw(blk.ln1.beta),
                qw=raw(at.q_proj.weight), qb=raw(at.q_proj.bias),
                kw=raw(at.k_proj.weight), kb=raw(at.k_proj.bias),
                vw=raw(at.v_proj.weight), vb=raw(at.v_proj.bias),
                ow=raw(at.out_proj.weight), ob=raw(at.out_proj.bias),
                ln2_g=raw(blk.ln2.gamma), ln2_b=raw(blk.ln2.beta),
                f1w=raw(blk.ffn1.weight), f1b=raw(blk.ffn1.bias),
                f2w=raw(blk.ffn2.weight), f2b=raw(blk.ffn2.bias)))
        out = dict(embed=raw(self.embedding.weight),
                   pos=raw(self.pos_embed), ln_f_g=raw(self.ln_f.gamma),
                   ln_f_b=raw(self.ln_f.beta), layers=layers)
        if not self._tie:
            out["head_w"] = raw(self.head.weight)
            out["head_b"] = raw(self.head.bias)
        return out

    def serving_step(self, S: int, TOT: int):
        """The engine-facing step-callable: one decode step over an
        ``S``-slot batch with PER-SLOT positions.

        Returns ``step(params, caches, tok, p) -> (new_caches, logits)``
        where ``caches`` is the static ``(L, 2, S, H, TOT, D)`` KV cache,
        ``tok`` is the ``(S,)`` int32 token fed at per-slot position ``p``
        (``(S,)`` int32, clipped into the cache), and ``logits`` is
        ``(S, vocab)`` for position ``p + 1``. Every op is row-independent
        (per-slot causal mask, per-slot KV scatter), so one slot's output is
        bit-identical regardless of what the other slots hold — the property
        the continuous-batching engine's bit-exactness contract rests on.
        ``_build_generate`` scans this same callable with ``p`` broadcast to
        a single position, so solo ``generate`` and the serving engine share
        one implementation of the decode math."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        H = self.blocks[0].attn._heads
        U = self._units
        D = U // H
        scale = 1.0 / math.sqrt(D)

        def ln(x, g, b, eps=1e-5):
            m = jnp.mean(x, axis=-1, keepdims=True)
            v = jnp.var(x, axis=-1, keepdims=True)
            return (x - m) * lax.rsqrt(v + eps) * g + b

        def step(params, caches, tok, p):
            rows = jnp.arange(S)
            pc = jnp.clip(p, 0, TOT - 1)
            x = params["embed"][tok] + params["pos"][pc]       # (S, U)
            x = _constrain_raw(x, "activations")
            mask = jnp.arange(TOT)[None, :] <= pc[:, None]     # (S, TOT)
            new_caches = caches
            for i, lp in enumerate(params["layers"]):
                h = ln(x, lp["ln1_g"], lp["ln1_b"])
                q = (h @ lp["qw"].T + lp["qb"]).reshape(S, H, D)
                k = (h @ lp["kw"].T + lp["kb"]).reshape(S, H, D)
                v = (h @ lp["vw"].T + lp["vb"]).reshape(S, H, D)
                # per-slot scatter: slot s writes only its own cache row at
                # its own position — dead/retired slots can't corrupt peers
                kv_dt = new_caches.dtype     # bf16 caches: cast, then store
                new_caches = new_caches.at[i, 0, rows, :, pc].set(
                    k.astype(kv_dt))
                new_caches = new_caches.at[i, 1, rows, :, pc].set(
                    v.astype(kv_dt))
                K = new_caches[i, 0]        # (S, H, TOT, D)
                V = new_caches[i, 1]
                s = jnp.einsum("bhd,bhtd->bht", q, K) * scale
                s = jnp.where(mask[:, None, :], s, -1e30)
                att = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("bht,bhtd->bhd", att, V).reshape(S, U)
                # all-gather the tp-sharded ctx/g before each row matmul:
                # the weight is replicated under the serving layout, so the
                # contraction stays a full local dot — never partial sums +
                # psum (the bit-exactness contract; mxtpu/serving/sharded.py)
                ctx = _constrain_raw(ctx, "activations")
                x = x + ctx @ lp["ow"].T + lp["ob"]
                g = ln(x, lp["ln2_g"], lp["ln2_b"])
                g = jax.nn.gelu(g @ lp["f1w"].T + lp["f1b"],
                                approximate=False)
                g = _constrain_raw(g, "activations")
                x = x + g @ lp["f2w"].T + lp["f2b"]
            h = ln(x, params["ln_f_g"], params["ln_f_b"])
            if self._tie:
                logits = h @ params["embed"].T                  # (S, vocab)
            else:
                logits = h @ params["head_w"].T + params["head_b"]
            # pin the carry sharding so the scanned/returned cache matches
            # the engine's canonical placement (trace-once across dispatches)
            new_caches = _constrain_raw(new_caches, "kv_cache")
            return new_caches, logits

        return step

    def serving_verify_step(self, S: int, TOT: int, K1: int):
        """Speculative-decode verifier: one forward scoring ``K1`` = k + 1
        consecutive positions per slot against the same paged KV cache.

        Returns ``step(params, caches, toks, p) -> (new_caches, logits)``
        where ``toks`` is ``(S, K1)`` int32 — ``toks[s, 0]`` is the slot's
        current token (what plain decode would feed at ``p[s]``) and
        ``toks[s, j]`` for ``j >= 1`` the j-th drafted token, fed at
        position ``p[s] + j`` — and ``logits`` is ``(S, K1, vocab)``:
        row ``j`` is the model's prediction for position ``p[s] + j + 1``.

        Bit-exactness with :meth:`serving_step` is structural, not
        approximate: the dense projections run on the flattened
        ``(S * K1, U)`` row batch (each row the same dot product the
        single-step path computes), all ``K1`` K/V rows are scattered
        before any query attends, and attention runs per drafted position
        ``j`` through the IDENTICAL ``"bhd,bhtd->bht"`` einsum with the
        causal mask ``t <= p + j`` — so query ``j`` sees exactly the rows
        sequential decode would have written by step ``j``. A rejected
        draft leaves garbage K/V rows above the accept point; they sit
        beyond every surviving query's mask and are overwritten in order
        by the next dispatch before anything attends them, so rollback is
        host cursor arithmetic only."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        H = self.blocks[0].attn._heads
        U = self._units
        D = U // H
        scale = 1.0 / math.sqrt(D)

        def ln(x, g, b, eps=1e-5):
            m = jnp.mean(x, axis=-1, keepdims=True)
            v = jnp.var(x, axis=-1, keepdims=True)
            return (x - m) * lax.rsqrt(v + eps) * g + b

        def step(params, caches, toks, p):
            rows = jnp.arange(S)
            # (S, K1) per-slot write positions p..p+K1-1, clipped like the
            # single-step path; clipped duplicates land on row TOT-1, which
            # no live query ever attends (max fed position is limit - 1)
            pcs = jnp.clip(p[:, None] + jnp.arange(K1)[None, :], 0, TOT - 1)
            x = params["embed"][toks] + params["pos"][pcs]     # (S, K1, U)
            x = _constrain_raw(x, "activations")
            # query j may see rows 0..p+j only — the rows sequential decode
            # would have written by its j-th step
            mask = jnp.arange(TOT)[None, None, :] <= pcs[:, :, None]
            new_caches = caches
            for i, lp in enumerate(params["layers"]):
                h = ln(x, lp["ln1_g"], lp["ln1_b"])
                flat = h.reshape(S * K1, U)       # per-row dots == decode's
                q = (flat @ lp["qw"].T + lp["qb"]).reshape(S, K1, H, D)
                k = (flat @ lp["kw"].T + lp["kb"]).reshape(S, K1, H, D)
                v = (flat @ lp["vw"].T + lp["vb"]).reshape(S, K1, H, D)
                kv_dt = new_caches.dtype
                # every position's row lands before any query attends; the
                # j-loop keeps writes ordered so a clipped collision at
                # TOT-1 resolves deterministically (last write wins)
                for j in range(K1):
                    new_caches = new_caches.at[i, 0, rows, :, pcs[:, j]].set(
                        k[:, j].astype(kv_dt))
                    new_caches = new_caches.at[i, 1, rows, :, pcs[:, j]].set(
                        v[:, j].astype(kv_dt))
                K = new_caches[i, 0]              # (S, H, TOT, D)
                V = new_caches[i, 1]
                ctxs = []
                for j in range(K1):
                    s = jnp.einsum("bhd,bhtd->bht", q[:, j], K) * scale
                    s = jnp.where(mask[:, j][:, None, :], s, -1e30)
                    att = jax.nn.softmax(s, axis=-1)
                    ctxs.append(jnp.einsum("bht,bhtd->bhd", att, V))
                ctx = jnp.stack(ctxs, axis=1).reshape(S, K1, U)
                # same all-gather-before-row-matmul contract as serving_step
                # (replicated ow/f2w under the serving layout: no psum)
                flatc = _constrain_raw(ctx.reshape(S * K1, U), "activations")
                x = x + (flatc @ lp["ow"].T + lp["ob"]).reshape(S, K1, U)
                g = ln(x, lp["ln2_g"], lp["ln2_b"])
                g = jax.nn.gelu(g.reshape(S * K1, U) @ lp["f1w"].T
                                + lp["f1b"], approximate=False)
                g = _constrain_raw(g, "activations")
                x = x + (g @ lp["f2w"].T + lp["f2b"]).reshape(S, K1, U)
            h = ln(x, params["ln_f_g"], params["ln_f_b"])
            hf = h.reshape(S * K1, U)
            if self._tie:
                logits = hf @ params["embed"].T
            else:
                logits = hf @ params["head_w"].T + params["head_b"]
            new_caches = _constrain_raw(new_caches, "kv_cache")
            return new_caches, logits.reshape(S, K1, self._vocab)

        return step

    def serving_sample(self):
        """Per-slot next-token selection shared by the serving decode and
        chunked-prefill programs (``serving/kv.py``): returns
        ``sample(logits (S, V), temp (S,), topk (S,), seed (S,), pos (S,))
        -> (S,) int32``.

        Every sampling parameter is a TRACED array, so a mixed batch of
        greedy and sampled slots — or a change in the mix between
        dispatches — reuses one compiled program. ``temp[s] == 0`` selects
        plain argmax, bit-identical to the pre-sampling greedy path (the
        engine's bit-exactness contract vs solo ``generate``);
        ``temp[s] > 0`` samples from the temperature-scaled, top-k-masked
        logits with a key derived as ``fold_in(PRNGKey(seed[s]), pos[s])``.
        Keying on the ABSOLUTE position makes a request's stream a pure
        function of (weights, prompt, temperature, top-k, seed): the same
        request re-submitted under any slot assignment, chunk boundary, or
        prefill/decode split reproduces the same tokens — the
        seed-determinism contract. ``topk[s] <= 0`` means no top-k
        truncation; ties at the k-th logit are all kept (deterministic)."""
        import jax
        import jax.numpy as jnp

        V = self._vocab

        def sample(logits, temp, topk, seed, pos):
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def one(lg, tm, k, sd, p):
                kk = jnp.clip(jnp.where(k <= 0, V, k), 1, V)
                thresh = jnp.sort(lg)[V - kk]          # k-th largest logit
                masked = jnp.where(lg >= thresh, lg, -jnp.inf)
                key = jax.random.fold_in(jax.random.PRNGKey(sd), p)
                return jax.random.categorical(
                    key, masked / jnp.maximum(tm, 1e-6)).astype(jnp.int32)

            sampled = jax.vmap(one)(logits, temp, topk, seed, pos)
            return jnp.where(temp > 0, sampled, greedy)

        return sample

    def _build_generate(self, B: int, P: int, TOT: int, greedy: bool):
        """One compiled decode program for (batch B, prompt bucket P, scan
        bucket TOT): the TRUE prompt length arrives as a traced scalar, so
        natural-length prompts share programs per bucket instead of
        recompiling per length. The scan body is :meth:`serving_step` with
        every slot at the same position; the greedy program takes no rng
        key (argmax needs none — dropping it keeps the donation/signature
        surface minimal)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        H = self.blocks[0].attn._heads
        D = self._units // H
        L = len(self.blocks)
        step = self.serving_step(B, TOT)

        def body_tok(params, caches, prev, prompt, t0, t):
            # prompt positions are FORCED; generated positions feed back
            tok = jnp.where(t < t0, prompt[:, jnp.minimum(t, P - 1)], prev)
            pos = jnp.full((B,), t, jnp.int32)
            return step(params, caches, tok, pos)

        if greedy:
            def run(params, prompt, t0):
                caches0 = jnp.zeros((L, 2, B, H, TOT, D),
                                    params["embed"].dtype)

                def body(carry, t):
                    caches, prev = carry
                    new_caches, logits = body_tok(params, caches, prev,
                                                  prompt, t0, t)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (new_caches, nxt), nxt

                init = (caches0, jnp.zeros((B,), jnp.int32))
                _, outs = lax.scan(body, init,
                                   jnp.arange(TOT, dtype=jnp.int32))
                return outs.T                                   # (B, TOT)
        else:
            def run(params, prompt, t0, key):
                caches0 = jnp.zeros((L, 2, B, H, TOT, D),
                                    params["embed"].dtype)

                def body(carry, t):
                    caches, prev, key = carry
                    new_caches, logits = body_tok(params, caches, prev,
                                                  prompt, t0, t)
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, logits, axis=-1) \
                        .astype(jnp.int32)
                    return (new_caches, nxt, key), nxt

                init = (caches0, jnp.zeros((B,), jnp.int32), key)
                _, outs = lax.scan(body, init,
                                   jnp.arange(TOT, dtype=jnp.int32))
                return outs.T                                   # (B, TOT)

        return jax.jit(run)

    def length_bucket(self, n: int) -> int:
        """32-token length bucket (capped at ``max_len``) — programs are
        shared per bucket; the serving KV admission uses the same rounding
        so engine caches and solo ``generate`` key identically."""
        return min(self._max_len, -(-n // 32) * 32)

    @staticmethod
    def batch_bucket(b: int) -> int:
        """Power-of-two batch bucket (1 stays 1): ragged last batches pad up
        instead of compiling a fresh decode program per exact batch size."""
        return 1 if b <= 1 else 1 << (b - 1).bit_length()

    def generate(self, tokens, max_new_tokens: int, greedy: bool = True,
                 seed: int = 0):
        """Autoregressive continuation: returns ``(B, T0 + max_new_tokens)``
        int tokens (prompt + generated). One compiled ``lax.scan`` over a
        static KV cache — the prompt prefills through the same step program,
        so decode costs one dispatch total, not one per token. Programs key
        on (batch bucket, prompt bucket, scan bucket): ragged batches pad to
        the next power of two and masked rows are sliced off the output."""
        import jax
        import jax.numpy as jnp

        from ... import autograd
        from ...ndarray.ndarray import NDArray
        from ...step_cache import ProgramCache
        raw = tokens.data if isinstance(tokens, NDArray) else jnp.asarray(tokens)
        B, T0 = raw.shape
        if T0 < 1:
            raise ValueError("generate needs a non-empty prompt (give a BOS "
                             "token for unconditional generation)")
        if any(p._data is None for p in self.collect_params().values()):
            with autograd.predict_mode():   # materialize deferred params
                self(NDArray(raw))
        total = T0 + int(max_new_tokens)
        if total > self._max_len:
            raise ValueError(f"prompt {T0} + {max_new_tokens} new exceeds "
                             f"max_len {self._max_len}")

        BB = self.batch_bucket(B)
        P, TOT = self.length_bucket(T0), self.length_bucket(total)
        key = (BB, P, TOT, bool(greedy))
        cache = getattr(self, "_gen_fns", None)
        if cache is None:
            cache = self._gen_fns = ProgramCache("generate")
        fn = cache.get_or_build(
            key, lambda: self._build_generate(BB, P, TOT, greedy))
        padded = jnp.zeros((BB, P), jnp.int32).at[:B, :T0].set(
            raw.astype(jnp.int32))
        if greedy:
            outs = fn(self._gen_params(), padded, jnp.int32(T0))
        else:
            outs = fn(self._gen_params(), padded, jnp.int32(T0),
                      jax.random.key(seed))
        # outs[t] is the token sampled AFTER position t; stitch prompt + tail
        gen = outs[:B, T0 - 1:total - 1]
        return NDArray(jnp.concatenate([raw.astype(jnp.int32), gen], axis=1))


_PRESETS = {
    # name: (units, layers, heads, max_len)
    "tiny": (64, 2, 2, 256),            # tests
    "small": (512, 6, 8, 1024),         # ~35M params at 16k vocab
    "base": (768, 12, 12, 1024),        # GPT-2 124M-class
    "flagship": (1024, 8, 16, 2048),    # the bench workload: MXU-dominated
    "wide": (2048, 4, 16, 2048),        # fewer/wider blocks: 2048x8192 FFN
                                        # matmuls saturate the MXU (64.9% MFU
                                        # measured on v5e vs 44% at d1024 L8)
}


def transformer_lm(preset: str = "small", vocab_size: int = 16384, **kwargs):
    """Factory over the preset table (model-zoo surface parity with
    ``vision.get_model``)."""
    try:
        units, layers, heads, max_len = _PRESETS[preset]
    except KeyError:
        raise ValueError(
            f"unknown preset {preset!r}; choose from {sorted(_PRESETS)}")
    cfg = dict(units=units, num_layers=layers, num_heads=heads,
               max_len=max_len)
    cfg.update(kwargs)
    return TransformerLM(vocab_size, **cfg)
