"""Model zoo — parity with ``python/mxnet/gluon/model_zoo/vision`` (SURVEY.md §2.5):
ResNet v1/v2 (18/34/50/101/152), VGG 11/13/16/19 (±BN), AlexNet, SqueezeNet 1.0/1.1,
DenseNet 121/161/169/201, MobileNet v1 (multipliers) & v2, Inception-V3, plus LeNet
(the reference's canonical MNIST example network, example/image-classification
train_mnist.py).

``pretrained=True`` requires a local weight mirror (zero-egress env) — see
gluon/utils.download.
"""

from __future__ import annotations

from typing import List, Optional

from ... import ndarray as nd
from .. import nn
from ..block import HybridBlock

__all__ = ["get_model", "get_resnet", "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "vgg11", "vgg13", "vgg16",
           "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "alexnet",
           "squeezenet1_0", "squeezenet1_1", "densenet121", "densenet161",
           "densenet169", "densenet201", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0", "mobilenet_v2_0_75",
           "mobilenet_v2_0_5", "mobilenet_v2_0_25", "inception_v3", "lenet", "LeNet"]


# ---------------------------------------------------------------------------
# ResNet (model_zoo/vision/resnet.py parity)
# ---------------------------------------------------------------------------


def _conv3x3(channels, stride, in_channels):
    return nn.Conv2D(channels, kernel_size=3, strides=stride, padding=1,
                     use_bias=False, in_channels=in_channels)


class BasicBlockV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(_conv3x3(channels, stride, in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels, 1, channels))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return nd.Activation(x + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, kernel_size=1, strides=stride))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(_conv3x3(channels // 4, 1, channels // 4))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, kernel_size=1, strides=1))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, kernel_size=1, strides=stride,
                                          use_bias=False, in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return nd.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = _conv3x3(channels, stride, in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels, 1, channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x)
        x = nd.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = _conv3x3(channels // 4, stride, channels // 4)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x)
        x = nd.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1,
                                                   in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels, prefix=""))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(_conv3x3(channels[0], 1, 0))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1,
                                                   in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
resnet_net_versions = [ResNetV1, ResNetV2]
resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version: int, num_layers: int, pretrained: bool = False, ctx=None,
               **kwargs) -> HybridBlock:
    block_type, layers, channels = resnet_spec[num_layers]
    resnet_class = resnet_net_versions[version - 1]
    block_class = resnet_block_versions[version - 1][block_type]
    net = resnet_class(block_class, layers, channels, **kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, f"resnet{num_layers}_v{version}", ctx)
    return net


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)


# ---------------------------------------------------------------------------
# VGG (model_zoo/vision/vgg.py parity)
# ---------------------------------------------------------------------------

vgg_spec = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], kernel_size=3, padding=1))
                    if batch_norm:
                        self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(strides=2))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _vgg(num_layers, batch_norm=False, pretrained=False, ctx=None, **kwargs):
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, batch_norm=batch_norm, **kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, f"vgg{num_layers}{'_bn' if batch_norm else ''}", ctx)
    return net


def vgg11(**kw):
    return _vgg(11, **kw)


def vgg13(**kw):
    return _vgg(13, **kw)


def vgg16(**kw):
    return _vgg(16, **kw)


def vgg19(**kw):
    return _vgg(19, **kw)


def vgg11_bn(**kw):
    return _vgg(11, batch_norm=True, **kw)


def vgg13_bn(**kw):
    return _vgg(13, batch_norm=True, **kw)


def vgg16_bn(**kw):
    return _vgg(16, batch_norm=True, **kw)


def vgg19_bn(**kw):
    return _vgg(19, batch_norm=True, **kw)


# ---------------------------------------------------------------------------
# AlexNet (model_zoo/vision/alexnet.py parity)
# ---------------------------------------------------------------------------


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(192, 5, padding=2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(384, 3, padding=1, activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, **kwargs):
    net = AlexNet(**kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, "alexnet", ctx)
    return net


# ---------------------------------------------------------------------------
# SqueezeNet (model_zoo/vision/squeezenet.py parity)
# ---------------------------------------------------------------------------


class _Fire(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.expand1 = nn.Conv2D(expand1x1, 1, activation="relu")
        self.expand3 = nn.Conv2D(expand3x3, 3, padding=1, activation="relu")

    def forward(self, x):
        x = self.squeeze(x)
        return nd.concat(self.expand1(x), self.expand3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version: str = "1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for sq, e1, e3 in [(16, 64, 64), (16, 64, 64), (32, 128, 128)]:
                    self.features.add(_Fire(sq, e1, e3))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for sq, e1, e3 in [(32, 128, 128), (48, 192, 192), (48, 192, 192),
                                   (64, 256, 256)]:
                    self.features.add(_Fire(sq, e1, e3))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                self.features.add(_Fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, 3, 2, activation="relu"))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for sq, e1, e3 in [(16, 64, 64), (16, 64, 64)]:
                    self.features.add(_Fire(sq, e1, e3))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for sq, e1, e3 in [(32, 128, 128), (32, 128, 128)]:
                    self.features.add(_Fire(sq, e1, e3))
                self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
                for sq, e1, e3 in [(48, 192, 192), (48, 192, 192), (64, 256, 256),
                                   (64, 256, 256)]:
                    self.features.add(_Fire(sq, e1, e3))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **_strip(kw))


def squeezenet1_1(**kw):
    return SqueezeNet("1.1", **_strip(kw))


def _strip(kw):
    kw.pop("pretrained", None)
    kw.pop("ctx", None)
    return kw


# ---------------------------------------------------------------------------
# DenseNet (model_zoo/vision/densenet.py parity)
# ---------------------------------------------------------------------------


def _make_dense_block(num_layers, bn_size, growth_rate, dropout, stage_index):
    out = nn.HybridSequential(prefix=f"stage{stage_index}_")
    with out.name_scope():
        for _ in range(num_layers):
            out.add(_DenseLayer(growth_rate, bn_size, dropout))
    return out


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(bn_size * growth_rate, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def forward(self, x):
        return nd.concat(x, self.body(x), dim=1)


def _make_transition(num_output_features):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, 1, use_bias=False))
    out.add(nn.AvgPool2D(2, 2))
    return out


densenet_spec = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config, bn_size=4,
                 dropout=0.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, 7, 2, 3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(3, 2, 1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_make_dense_block(num_layers, bn_size, growth_rate,
                                                    dropout, i + 1))
                num_features += num_layers * growth_rate
                if i != len(block_config) - 1:
                    num_features //= 2
                    self.features.add(_make_transition(num_features))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def _densenet(num_layers, **kwargs):
    init_f, growth, cfg = densenet_spec[num_layers]
    return DenseNet(init_f, growth, cfg, **_strip(kwargs))


def densenet121(**kw):
    return _densenet(121, **kw)


def densenet161(**kw):
    return _densenet(161, **kw)


def densenet169(**kw):
    return _densenet(169, **kw)


def densenet201(**kw):
    return _densenet(201, **kw)


# ---------------------------------------------------------------------------
# MobileNet v1/v2 (model_zoo/vision/mobilenet.py parity)
# ---------------------------------------------------------------------------


def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1, active=True,
              relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group, use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(nn.HybridLambda(lambda x: nd.clip(x, 0.0, 6.0)) if relu6
                else nn.Activation("relu"))


class MobileNet(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1)
            dw_channels = [int(x * multiplier) for x in
                           [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
            channels = [int(x * multiplier) for x in
                        [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
            strides = [1, 2, 1, 2, 1, 2] + [1] * 5 + [2, 1]
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv(self.features, dwc, 3, s, 1, num_group=dwc)  # depthwise
                _add_conv(self.features, c, 1, 1, 0)  # pointwise
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


class _LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        self.out = nn.HybridSequential(prefix="")
        _add_conv(self.out, in_channels * t, relu6=True)
        _add_conv(self.out, in_channels * t, 3, stride, 1, num_group=in_channels * t,
                  relu6=True)
        _add_conv(self.out, channels, active=False)

    def forward(self, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1, relu6=True)
            in_c = [int(multiplier * x) for x in
                    [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                    + [160] * 3]
            channels = [int(multiplier * x) for x in
                        [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3 + [160] * 3
                        + [320]]
            ts = [1] + [6] * 16
            strides = [1, 2, 1, 2, 1, 1, 2, 1, 1, 1, 1, 1, 1, 2, 1, 1, 1]
            for ic, c, t, s in zip(in_c, channels, ts, strides):
                self.features.add(_LinearBottleneck(ic, c, t, s))
            last = int(1280 * multiplier) if multiplier > 1.0 else 1280
            _add_conv(self.features, last, relu6=True)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            self.output.add(nn.Conv2D(classes, 1, use_bias=False))
            self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def mobilenet1_0(**kw):
    return MobileNet(1.0, **_strip(kw))


def mobilenet0_75(**kw):
    return MobileNet(0.75, **_strip(kw))


def mobilenet0_5(**kw):
    return MobileNet(0.5, **_strip(kw))


def mobilenet0_25(**kw):
    return MobileNet(0.25, **_strip(kw))


def mobilenet_v2_1_0(**kw):
    return MobileNetV2(1.0, **_strip(kw))


def mobilenet_v2_0_75(**kw):
    return MobileNetV2(0.75, **_strip(kw))


def mobilenet_v2_0_5(**kw):
    return MobileNetV2(0.5, **_strip(kw))


def mobilenet_v2_0_25(**kw):
    return MobileNetV2(0.25, **_strip(kw))


# ---------------------------------------------------------------------------
# Inception V3 (model_zoo/vision/inception.py parity)
# ---------------------------------------------------------------------------


def _make_basic_conv(channels, kernel, stride=1, padding=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel, stride, padding, use_bias=False))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


class _Branch(HybridBlock):
    def __init__(self, branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = branches
        for i, b in enumerate(branches):
            self.register_child(b, f"branch{i}")

    def forward(self, x):
        return nd.concat(*[b(x) for b in self.branches], dim=1)


def _make_A(pool_features, prefix):
    b1 = _make_basic_conv(64, 1)
    b2 = nn.HybridSequential(); b2.add(_make_basic_conv(48, 1)); b2.add(_make_basic_conv(64, 5, padding=2))
    b3 = nn.HybridSequential(); b3.add(_make_basic_conv(64, 1)); b3.add(_make_basic_conv(96, 3, padding=1)); b3.add(_make_basic_conv(96, 3, padding=1))
    b4 = nn.HybridSequential(); b4.add(nn.AvgPool2D(3, 1, 1)); b4.add(_make_basic_conv(pool_features, 1))
    return _Branch([b1, b2, b3, b4])


def _make_B():
    b1 = _make_basic_conv(384, 3, 2)
    b2 = nn.HybridSequential(); b2.add(_make_basic_conv(64, 1)); b2.add(_make_basic_conv(96, 3, padding=1)); b2.add(_make_basic_conv(96, 3, 2))
    b3 = nn.HybridSequential(); b3.add(nn.MaxPool2D(3, 2))
    return _Branch([b1, b2, b3])


def _make_C(channels_7x7):
    b1 = _make_basic_conv(192, 1)
    c = channels_7x7
    b2 = nn.HybridSequential()
    for ch, k, p in [(c, (1, 7), (0, 3)), (192, (7, 1), (3, 0))]:
        b2.add(_make_basic_conv(ch, k, padding=p))
    b2_pre = nn.HybridSequential(); b2_pre.add(_make_basic_conv(c, 1)); b2_pre.add(b2)
    b3 = nn.HybridSequential()
    b3.add(_make_basic_conv(c, 1))
    for ch, k, p in [(c, (7, 1), (3, 0)), (c, (1, 7), (0, 3)), (c, (7, 1), (3, 0)),
                     (192, (1, 7), (0, 3))]:
        b3.add(_make_basic_conv(ch, k, padding=p))
    b4 = nn.HybridSequential(); b4.add(nn.AvgPool2D(3, 1, 1)); b4.add(_make_basic_conv(192, 1))
    return _Branch([b1, b2_pre, b3, b4])


def _make_D():
    b1 = nn.HybridSequential(); b1.add(_make_basic_conv(192, 1)); b1.add(_make_basic_conv(320, 3, 2))
    b2 = nn.HybridSequential()
    b2.add(_make_basic_conv(192, 1))
    b2.add(_make_basic_conv(192, (1, 7), padding=(0, 3)))
    b2.add(_make_basic_conv(192, (7, 1), padding=(3, 0)))
    b2.add(_make_basic_conv(192, 3, 2))
    b3 = nn.HybridSequential(); b3.add(nn.MaxPool2D(3, 2))
    return _Branch([b1, b2, b3])


class _SplitConcat(HybridBlock):
    def __init__(self, pre, left, right, **kwargs):
        super().__init__(**kwargs)
        self.pre, self.left, self.right = pre, left, right
        self.register_child(pre, "pre")
        self.register_child(left, "left")
        self.register_child(right, "right")

    def forward(self, x):
        x = self.pre(x)
        return nd.concat(self.left(x), self.right(x), dim=1)


def _make_E():
    b1 = _make_basic_conv(320, 1)
    b2 = _SplitConcat(_make_basic_conv(384, 1),
                      _make_basic_conv(384, (1, 3), padding=(0, 1)),
                      _make_basic_conv(384, (3, 1), padding=(1, 0)))
    pre3 = nn.HybridSequential()
    pre3.add(_make_basic_conv(448, 1))
    pre3.add(_make_basic_conv(384, 3, padding=1))
    b3 = _SplitConcat(pre3, _make_basic_conv(384, (1, 3), padding=(0, 1)),
                      _make_basic_conv(384, (3, 1), padding=(1, 0)))
    b4 = nn.HybridSequential(); b4.add(nn.AvgPool2D(3, 1, 1)); b4.add(_make_basic_conv(192, 1))
    return _Branch([b1, b2, b3, b4])


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_make_basic_conv(32, 3, 2))
            self.features.add(_make_basic_conv(32, 3))
            self.features.add(_make_basic_conv(64, 3, padding=1))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(_make_basic_conv(80, 1))
            self.features.add(_make_basic_conv(192, 3))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(_make_A(32, "A1_"))
            self.features.add(_make_A(64, "A2_"))
            self.features.add(_make_A(64, "A3_"))
            self.features.add(_make_B())
            for c in (128, 160, 160, 192):
                self.features.add(_make_C(c))
            self.features.add(_make_D())
            self.features.add(_make_E())
            self.features.add(_make_E())
            self.features.add(nn.AvgPool2D(8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def inception_v3(**kw):
    return Inception3(**_strip(kw))


# ---------------------------------------------------------------------------
# LeNet (reference example/image-classification/symbols/lenet.py parity)
# ---------------------------------------------------------------------------


class LeNet(HybridBlock):
    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(20, 5, activation="tanh"))
            self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Conv2D(50, 5, activation="tanh"))
            self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(500, activation="tanh"))
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def lenet(**kw):
    return LeNet(**_strip(kw))


# ---------------------------------------------------------------------------
# registry (model_zoo/__init__.py get_model parity)
# ---------------------------------------------------------------------------

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3, "lenet": lenet,
}


def get_model(name: str, **kwargs) -> HybridBlock:
    name = name.lower()
    if name not in _models:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_models)}")
    return _models[name](**kwargs)
