"""Model zoo — capability parity with ``python/mxnet/gluon/model_zoo/vision``
(SURVEY.md §2.5): ResNet v1/v2 (18/34/50/101/152), VGG 11/13/16/19 (±BN),
AlexNet, SqueezeNet 1.0/1.1, DenseNet 121/161/169/201, MobileNet v1
(multipliers) & v2, Inception-V3, plus LeNet (the reference's canonical MNIST
network, example/image-classification/symbols/lenet.py).

Design: unlike the reference (one hand-written ``HybridBlock`` subclass per
block variant), every architecture here is assembled from a declarative spec by
a handful of generic cells:

* ``_cna``       — conv[+norm][+act] unit appended to a sequence
* ``_Residual``  — y = tail(main(stem(x)) + shortcut(stem(x))), covering both
                   post-activation (v1) and pre-activation (v2) residual styles
* ``_Fork``      — channel-concat of parallel branches (SqueezeNet Fire,
                   Inception mixed blocks)
* ``_DenseCell`` — y = concat(x, body(x)) (DenseNet)
* ``_Net``       — features → output container shared by all families

Family tables (``_RESNET_SPEC``, ``_VGG_SPEC``, …) carry the published layer
counts/widths (architectural constants from the papers). Deviation from the
reference: all convolutions feeding a BatchNorm use ``use_bias=False`` (the
reference leaves default biases on a few 1x1 convs in BottleneckV1 — redundant
before BN).

``pretrained=True`` requires a local weight mirror (zero-egress env) — see
gluon/utils.download.
"""

from __future__ import annotations

from ... import ndarray as nd
from .. import nn
from ..block import HybridBlock

__all__ = ["get_model", "get_resnet", "resnet18_v1", "resnet34_v1", "resnet50_v1",
           "resnet101_v1", "resnet152_v1", "resnet18_v2", "resnet34_v2",
           "resnet50_v2", "resnet101_v2", "resnet152_v2", "vgg11", "vgg13", "vgg16",
           "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn", "alexnet",
           "squeezenet1_0", "squeezenet1_1", "densenet121", "densenet161",
           "densenet169", "densenet201", "mobilenet1_0", "mobilenet0_75",
           "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0", "mobilenet_v2_0_75",
           "mobilenet_v2_0_5", "mobilenet_v2_0_25", "inception_v3", "lenet", "LeNet"]


# ---------------------------------------------------------------------------
# generic cells
# ---------------------------------------------------------------------------


def _seq(*blocks, prefix=""):
    s = nn.HybridSequential(prefix=prefix)
    for b in blocks:
        s.add(b)
    return s


def _act(name):
    if name == "relu6":
        return nn.HybridLambda(lambda x: nd.clip(x, 0.0, 6.0))
    return nn.Activation(name)


def _cna(seq, ch, k=1, s=1, p=0, *, g=1, norm=True, act="relu", bias=None,
         eps=1e-5):
    """Append a conv[+BatchNorm][+activation] unit to ``seq``.

    ``bias`` defaults to False when a norm follows (redundant otherwise) and
    True for bare convs.
    """
    if bias is None:
        bias = not norm
    seq.add(nn.Conv2D(ch, kernel_size=k, strides=s, padding=p, groups=g,
                      use_bias=bias))
    if norm:
        seq.add(nn.BatchNorm(epsilon=eps))
    if act:
        seq.add(_act(act))
    return seq


class _Residual(HybridBlock):
    """Generic residual cell: ``y = tail(main(h) + shortcut(h))`` where
    ``h = stem(x)`` and the identity path bypasses the stem.

    * post-activation style (ResNet v1): stem=None, shortcut=proj+BN,
      tail='relu'
    * pre-activation style (ResNet v2): stem=BN+relu (shared by main and
      projection shortcut), tail=None, identity = original ``x``
    * plain additive skip (MobileNetV2): only ``main``
    """

    def __init__(self, main, shortcut=None, stem=None, tail=None, **kwargs):
        super().__init__(**kwargs)
        self.main = main
        self.shortcut = shortcut
        self.stem = stem
        self._tail = tail

    def forward(self, x):
        identity = x
        h = self.stem(x) if self.stem is not None else x
        if self.shortcut is not None:
            identity = self.shortcut(h)
        y = self.main(h) + identity
        if self._tail:
            y = nd.Activation(y, act_type=self._tail)
        return y


class _Fork(HybridBlock):
    """Run branches in parallel on the same input and concat along channels."""

    def __init__(self, *branches, **kwargs):
        super().__init__(**kwargs)
        self.branches = list(branches)
        for i, b in enumerate(self.branches):
            self.register_child(b, f"branch{i}")

    def forward(self, x):
        return nd.concat(*[b(x) for b in self.branches], dim=1)


class _DenseCell(HybridBlock):
    """DenseNet connectivity: output is ``concat(x, body(x))``."""

    def __init__(self, body, **kwargs):
        super().__init__(**kwargs)
        self.body = body

    def forward(self, x):
        return nd.concat(x, self.body(x), dim=1)


class _Net(HybridBlock):
    """features → output container shared by every zoo family.

    Takes a ``build`` thunk returning ``(features, output)`` and runs it inside
    this block's ``name_scope`` so parameter names are net-relative and
    deterministic (required for save_parameters/load_parameters round-trips
    between instances)."""

    def __init__(self, build, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features, self.output = build()

    def forward(self, x):
        return self.output(self.features(x))


# ---------------------------------------------------------------------------
# ResNet v1/v2 — spec-driven (capability parity: model_zoo/vision/resnet.py)
# ---------------------------------------------------------------------------

# depth -> (unit kind, units per stage, stage widths incl. stem width)
_RESNET_SPEC = {
    18: ("basic", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}

# unit kind -> conv stack as (width(out_ch), kernel, stride, pad) rows;
# `s` marks where the stage stride lands, matching the reference placement
# (v1 bottleneck strides its first 1x1; v2 bottleneck strides the 3x3).


def _resnet_convs(kind, c, s, version):
    if kind == "basic":
        return [(c, 3, s, 1), (c, 3, 1, 1)]
    if version == 1:
        return [(c // 4, 1, s, 0), (c // 4, 3, 1, 1), (c, 1, 1, 0)]
    return [(c // 4, 1, 1, 0), (c // 4, 3, s, 1), (c, 1, 1, 0)]


def _resnet_unit(version, kind, c, s, project):
    convs = _resnet_convs(kind, c, s, version)
    main = nn.HybridSequential(prefix="")
    if version == 1:
        # conv-BN pairs, relu between pairs, residual add then relu (tail)
        for i, (w, k, st, pd) in enumerate(convs):
            _cna(main, w, k, st, pd, act="relu" if i < len(convs) - 1 else None)
        shortcut = _cna(nn.HybridSequential(prefix=""), c, 1, s,
                        act=None) if project else None
        return _Residual(main, shortcut, tail="relu")
    # v2: shared BN+relu stem, then conv / (BN+relu+conv)* — no norm after the
    # last conv; the projection shortcut consumes the stem output.
    stem = _seq(nn.BatchNorm(), nn.Activation("relu"))
    for i, (w, k, st, pd) in enumerate(convs):
        if i > 0:
            main.add(nn.BatchNorm())
            main.add(nn.Activation("relu"))
        main.add(nn.Conv2D(w, kernel_size=k, strides=st, padding=pd,
                           use_bias=False))
    shortcut = nn.Conv2D(c, kernel_size=1, strides=s,
                         use_bias=False) if project else None
    return _Residual(main, shortcut, stem=stem)


def _resnet_stage(version, kind, n_units, c, in_c, stride, index):
    stage = nn.HybridSequential(prefix=f"stage{index}_")
    with stage.name_scope():
        stage.add(_resnet_unit(version, kind, c, stride,
                               project=(stride != 1 or in_c != c)))
        for _ in range(n_units - 1):
            stage.add(_resnet_unit(version, kind, c, 1, project=False))
    return stage


def get_resnet(version: int, num_layers: int, pretrained: bool = False, ctx=None,
               classes: int = 1000, thumbnail: bool = False, **kwargs) -> HybridBlock:
    """Build a ResNet. ``thumbnail=True`` swaps the 7x7/maxpool stem for a bare
    3x3 (CIFAR-style input)."""
    if version not in (1, 2):
        raise ValueError(f"resnet version must be 1 or 2, got {version}")
    kind, units, widths = _RESNET_SPEC[num_layers]

    def build():
        feats = nn.HybridSequential(prefix="")
        if version == 2:
            feats.add(nn.BatchNorm(scale=False, center=False))  # input standardizer
        if thumbnail:
            _cna(feats, widths[0], 3, 1, 1, norm=False, act=None, bias=False)
        else:
            _cna(feats, widths[0], 7, 2, 3, act="relu")
            feats.add(nn.MaxPool2D(3, 2, 1))
        in_c = widths[0]
        for i, (n, c) in enumerate(zip(units, widths[1:])):
            feats.add(_resnet_stage(version, kind, n, c, in_c,
                                    1 if i == 0 else 2, i + 1))
            in_c = c
        if version == 2:
            feats.add(nn.BatchNorm())
            feats.add(nn.Activation("relu"))
        feats.add(nn.GlobalAvgPool2D())
        feats.add(nn.Flatten())
        return feats, nn.Dense(classes, in_units=in_c)

    net = _Net(build, **kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, f"resnet{num_layers}_v{version}", ctx)
    return net


def _resnet_factory(version, depth):
    def make(**kw):
        return get_resnet(version, depth, **kw)
    make.__name__ = f"resnet{depth}_v{version}"
    return make


resnet18_v1 = _resnet_factory(1, 18)
resnet34_v1 = _resnet_factory(1, 34)
resnet50_v1 = _resnet_factory(1, 50)
resnet101_v1 = _resnet_factory(1, 101)
resnet152_v1 = _resnet_factory(1, 152)
resnet18_v2 = _resnet_factory(2, 18)
resnet34_v2 = _resnet_factory(2, 34)
resnet50_v2 = _resnet_factory(2, 50)
resnet101_v2 = _resnet_factory(2, 101)
resnet152_v2 = _resnet_factory(2, 152)


# ---------------------------------------------------------------------------
# VGG — spec-driven (capability parity: model_zoo/vision/vgg.py)
# ---------------------------------------------------------------------------

# depth -> convs-per-stage; widths are fixed across depths
_VGG_SPEC = {11: [1, 1, 2, 2, 2], 13: [2, 2, 2, 2, 2],
             16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}
_VGG_WIDTHS = [64, 128, 256, 512, 512]


def _vgg(depth, batch_norm=False, pretrained=False, ctx=None, classes=1000,
         **kwargs):
    def build():
        feats = nn.HybridSequential(prefix="")
        for reps, width in zip(_VGG_SPEC[depth], _VGG_WIDTHS):
            for _ in range(reps):
                _cna(feats, width, 3, 1, 1, norm=batch_norm, act="relu",
                     bias=True)
            feats.add(nn.MaxPool2D(strides=2))
        for _ in range(2):
            feats.add(nn.Dense(4096, activation="relu"))
            feats.add(nn.Dropout(0.5))
        return feats, nn.Dense(classes)

    net = _Net(build, **kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, f"vgg{depth}{'_bn' if batch_norm else ''}", ctx)
    return net


def _vgg_factory(depth, bn):
    def make(**kw):
        return _vgg(depth, batch_norm=bn, **kw)
    make.__name__ = f"vgg{depth}{'_bn' if bn else ''}"
    return make


vgg11, vgg13, vgg16, vgg19 = (_vgg_factory(d, False) for d in (11, 13, 16, 19))
vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn = (_vgg_factory(d, True)
                                          for d in (11, 13, 16, 19))


# ---------------------------------------------------------------------------
# AlexNet — spec-driven (capability parity: model_zoo/vision/alexnet.py)
# ---------------------------------------------------------------------------

# (out_ch, kernel, stride, pad, maxpool-after?)
_ALEXNET_SPEC = [(64, 11, 4, 2, True), (192, 5, 1, 2, True), (384, 3, 1, 1, False),
                 (256, 3, 1, 1, False), (256, 3, 1, 1, True)]


def alexnet(pretrained=False, ctx=None, classes=1000, **kwargs):
    def build():
        feats = nn.HybridSequential(prefix="")
        for ch, k, s, p, pool in _ALEXNET_SPEC:
            _cna(feats, ch, k, s, p, norm=False, act="relu", bias=True)
            if pool:
                feats.add(nn.MaxPool2D(3, 2))
        feats.add(nn.Flatten())
        for _ in range(2):
            feats.add(nn.Dense(4096, activation="relu"))
            feats.add(nn.Dropout(0.5))
        return feats, nn.Dense(classes)

    net = _Net(build, **kwargs)
    if pretrained:
        from .model_store import load_pretrained
        load_pretrained(net, "alexnet", ctx)
    return net


# ---------------------------------------------------------------------------
# SqueezeNet — spec-driven (capability parity: model_zoo/vision/squeezenet.py)
# ---------------------------------------------------------------------------


def _fire(squeeze, expand):
    """Fire module: 1x1 squeeze then parallel 1x1/3x3 expand, concatenated."""
    e1 = _cna(nn.HybridSequential(prefix=""), expand, 1, norm=False, bias=True)
    e3 = _cna(nn.HybridSequential(prefix=""), expand, 3, 1, 1, norm=False,
              bias=True)
    return _seq(
        _cna(nn.HybridSequential(prefix=""), squeeze, 1, norm=False, bias=True),
        _Fork(e1, e3))


# version -> (stem (ch,k,s), fire squeeze widths grouped by pool boundaries)
_SQUEEZENET_SPEC = {
    "1.0": ((96, 7, 2), [[16, 16, 32], [32, 48, 48, 64], [64]]),
    "1.1": ((64, 3, 2), [[16, 16], [32, 32], [48, 48, 64, 64]]),
}


def _squeezenet(version, classes=1000, **kwargs):
    (ch, k, s), groups = _SQUEEZENET_SPEC[version]

    def build():
        feats = nn.HybridSequential(prefix="")
        _cna(feats, ch, k, s, norm=False, act="relu", bias=True)
        for squeezes in groups:
            feats.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq in squeezes:
                feats.add(_fire(sq, sq * 4))
        feats.add(nn.Dropout(0.5))
        out = nn.HybridSequential(prefix="")
        _cna(out, classes, 1, norm=False, act="relu", bias=True)
        out.add(nn.GlobalAvgPool2D())
        out.add(nn.Flatten())
        return feats, out

    return _Net(build, **kwargs)


def squeezenet1_0(**kw):
    return _squeezenet("1.0", **_strip(kw))


def squeezenet1_1(**kw):
    return _squeezenet("1.1", **_strip(kw))


def _strip(kw):
    if kw.pop("pretrained", False):
        raise NotImplementedError(
            "pretrained weights are not published for this family; load a local "
            "checkpoint via net.load_parameters() instead")
    kw.pop("ctx", None)
    return kw


# ---------------------------------------------------------------------------
# DenseNet — spec-driven (capability parity: model_zoo/vision/densenet.py)
# ---------------------------------------------------------------------------

# depth -> (stem width, growth rate, layers per dense block)
_DENSENET_SPEC = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
}


def _bn_relu_conv(seq, ch, k, p=0):
    seq.add(nn.BatchNorm())
    seq.add(nn.Activation("relu"))
    seq.add(nn.Conv2D(ch, kernel_size=k, padding=p, use_bias=False))
    return seq


def _dense_block(n_layers, growth, bn_size, dropout, index):
    block = nn.HybridSequential(prefix=f"stage{index}_")
    with block.name_scope():
        for _ in range(n_layers):
            body = nn.HybridSequential(prefix="")
            _bn_relu_conv(body, bn_size * growth, 1)
            _bn_relu_conv(body, growth, 3, 1)
            if dropout:
                body.add(nn.Dropout(dropout))
            block.add(_DenseCell(body))
    return block


def _densenet(depth, bn_size=4, dropout=0.0, classes=1000, **kwargs):
    stem_w, growth, blocks = _DENSENET_SPEC[depth]

    def build():
        feats = nn.HybridSequential(prefix="")
        _cna(feats, stem_w, 7, 2, 3, act="relu")
        feats.add(nn.MaxPool2D(3, 2, 1))
        width = stem_w
        for i, n in enumerate(blocks):
            feats.add(_dense_block(n, growth, bn_size, dropout, i + 1))
            width += n * growth
            if i != len(blocks) - 1:
                width //= 2
                feats.add(_bn_relu_conv(nn.HybridSequential(prefix=""), width, 1))
                feats.add(nn.AvgPool2D(2, 2))
        feats.add(nn.BatchNorm())
        feats.add(nn.Activation("relu"))
        feats.add(nn.GlobalAvgPool2D())
        feats.add(nn.Flatten())
        return feats, nn.Dense(classes)

    return _Net(build, **kwargs)


def densenet121(**kw):
    return _densenet(121, **_strip(kw))


def densenet161(**kw):
    return _densenet(161, **_strip(kw))


def densenet169(**kw):
    return _densenet(169, **_strip(kw))


def densenet201(**kw):
    return _densenet(201, **_strip(kw))


# ---------------------------------------------------------------------------
# MobileNet v1/v2 — spec-driven (capability parity: model_zoo/vision/mobilenet.py)
# ---------------------------------------------------------------------------

# v1: (pointwise out width, stride of the preceding depthwise) per unit
_MOBILENET_V1_SPEC = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
                      (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
                      (1024, 2), (1024, 1)]

# v2: (expansion t, out width, stride) per inverted-residual unit
_MOBILENET_V2_SPEC = [(1, 16, 1), (6, 24, 2), (6, 24, 1), (6, 32, 2), (6, 32, 1),
                      (6, 32, 1), (6, 64, 2), (6, 64, 1), (6, 64, 1), (6, 64, 1),
                      (6, 96, 1), (6, 96, 1), (6, 96, 1), (6, 160, 2),
                      (6, 160, 1), (6, 160, 1), (6, 320, 1)]


def _mobilenet_v1(multiplier=1.0, classes=1000, **kwargs):
    def build():
        feats = nn.HybridSequential(prefix="")
        width = int(32 * multiplier)
        _cna(feats, width, 3, 2, 1)
        for out_w, stride in _MOBILENET_V1_SPEC:
            out_w = int(out_w * multiplier)
            _cna(feats, width, 3, stride, 1, g=width)   # depthwise
            _cna(feats, out_w, 1)                       # pointwise
            width = out_w
        feats.add(nn.GlobalAvgPool2D())
        feats.add(nn.Flatten())
        return feats, nn.Dense(classes)

    return _Net(build, **kwargs)


def _inverted_residual(in_w, t, out_w, stride):
    body = nn.HybridSequential(prefix="")
    mid = in_w * t
    _cna(body, mid, 1, act="relu6")
    _cna(body, mid, 3, stride, 1, g=mid, act="relu6")
    _cna(body, out_w, 1, act=None)  # linear projection
    if stride == 1 and in_w == out_w:
        return _Residual(body)
    return body


def _mobilenet_v2(multiplier=1.0, classes=1000, **kwargs):
    def build():
        feats = nn.HybridSequential(prefix="features_")
        width = int(32 * multiplier)
        _cna(feats, width, 3, 2, 1, act="relu6")
        for t, out_w, stride in _MOBILENET_V2_SPEC:
            out_w = int(out_w * multiplier)
            feats.add(_inverted_residual(width, t, out_w, stride))
            width = out_w
        last = int(1280 * multiplier) if multiplier > 1.0 else 1280
        _cna(feats, last, 1, act="relu6")
        feats.add(nn.GlobalAvgPool2D())
        out = nn.HybridSequential(prefix="output_")
        out.add(nn.Conv2D(classes, 1, use_bias=False))
        out.add(nn.Flatten())
        return feats, out

    return _Net(build, **kwargs)


def _mobilenet_factory(builder, multiplier, name):
    def make(**kw):
        return builder(multiplier, **_strip(kw))
    make.__name__ = name
    return make


mobilenet1_0 = _mobilenet_factory(_mobilenet_v1, 1.0, "mobilenet1_0")
mobilenet0_75 = _mobilenet_factory(_mobilenet_v1, 0.75, "mobilenet0_75")
mobilenet0_5 = _mobilenet_factory(_mobilenet_v1, 0.5, "mobilenet0_5")
mobilenet0_25 = _mobilenet_factory(_mobilenet_v1, 0.25, "mobilenet0_25")
mobilenet_v2_1_0 = _mobilenet_factory(_mobilenet_v2, 1.0, "mobilenet_v2_1_0")
mobilenet_v2_0_75 = _mobilenet_factory(_mobilenet_v2, 0.75, "mobilenet_v2_0_75")
mobilenet_v2_0_5 = _mobilenet_factory(_mobilenet_v2, 0.5, "mobilenet_v2_0_5")
mobilenet_v2_0_25 = _mobilenet_factory(_mobilenet_v2, 0.25, "mobilenet_v2_0_25")


# ---------------------------------------------------------------------------
# Inception V3 — spec-driven (capability parity: model_zoo/vision/inception.py)
# ---------------------------------------------------------------------------
#
# Branch mini-language: a branch is a list of unit specs; each unit is either
# ("conv", ch, kernel, stride, pad), ("avg", k, s, p), ("max", k, s), or a
# nested ("fork", [branch, ...]) for the v3 "E" split-concat tails.


def _inception_branch(units):
    seq = nn.HybridSequential(prefix="")
    for u in units:
        kind = u[0]
        if kind == "conv":
            _, ch, k, s, p = u
            _cna(seq, ch, k, s, p, eps=0.001)
        elif kind == "avg":
            seq.add(nn.AvgPool2D(u[1], u[2], u[3]))
        elif kind == "max":
            seq.add(nn.MaxPool2D(u[1], u[2]))
        elif kind == "fork":
            seq.add(_Fork(*[_inception_branch(b) for b in u[1]]))
        else:
            raise ValueError(f"unknown inception unit kind {kind!r}")
    return seq


def _mixed(*branches):
    return _Fork(*[_inception_branch(b) for b in branches])


def _conv(ch, k, s=1, p=0):
    return ("conv", ch, k, s, p)


def _inception_a(pool_w):
    return _mixed(
        [_conv(64, 1)],
        [_conv(48, 1), _conv(64, 5, 1, 2)],
        [_conv(64, 1), _conv(96, 3, 1, 1), _conv(96, 3, 1, 1)],
        [("avg", 3, 1, 1), _conv(pool_w, 1)])


def _inception_b():
    return _mixed(
        [_conv(384, 3, 2)],
        [_conv(64, 1), _conv(96, 3, 1, 1), _conv(96, 3, 2)],
        [("max", 3, 2)])


def _inception_c(w7):
    return _mixed(
        [_conv(192, 1)],
        [_conv(w7, 1), _conv(w7, (1, 7), 1, (0, 3)), _conv(192, (7, 1), 1, (3, 0))],
        [_conv(w7, 1), _conv(w7, (7, 1), 1, (3, 0)), _conv(w7, (1, 7), 1, (0, 3)),
         _conv(w7, (7, 1), 1, (3, 0)), _conv(192, (1, 7), 1, (0, 3))],
        [("avg", 3, 1, 1), _conv(192, 1)])


def _inception_d():
    return _mixed(
        [_conv(192, 1), _conv(320, 3, 2)],
        [_conv(192, 1), _conv(192, (1, 7), 1, (0, 3)),
         _conv(192, (7, 1), 1, (3, 0)), _conv(192, 3, 2)],
        [("max", 3, 2)])


def _inception_e():
    split = [[_conv(384, (1, 3), 1, (0, 1))], [_conv(384, (3, 1), 1, (1, 0))]]
    return _mixed(
        [_conv(320, 1)],
        [_conv(384, 1), ("fork", split)],
        [_conv(448, 1), _conv(384, 3, 1, 1), ("fork", split)],
        [("avg", 3, 1, 1), _conv(192, 1)])


def inception_v3(classes=1000, **kw):
    kw = _strip(kw)

    def build():
        feats = nn.HybridSequential(prefix="")
        for ch, k, s, p in [(32, 3, 2, 0), (32, 3, 1, 0), (64, 3, 1, 1)]:
            _cna(feats, ch, k, s, p, eps=0.001)
        feats.add(nn.MaxPool2D(3, 2))
        for ch, k in [(80, 1), (192, 3)]:
            _cna(feats, ch, k, eps=0.001)
        feats.add(nn.MaxPool2D(3, 2))
        for pool_w in (32, 64, 64):
            feats.add(_inception_a(pool_w))
        feats.add(_inception_b())
        for w7 in (128, 160, 160, 192):
            feats.add(_inception_c(w7))
        feats.add(_inception_d())
        feats.add(_inception_e())
        feats.add(_inception_e())
        feats.add(nn.AvgPool2D(8))
        feats.add(nn.Dropout(0.5))
        feats.add(nn.Flatten())
        return feats, nn.Dense(classes)

    return _Net(build, **kw)


# ---------------------------------------------------------------------------
# LeNet (reference example/image-classification/symbols/lenet.py parity)
# ---------------------------------------------------------------------------


class LeNet(HybridBlock):
    """Classic LeNet-5-style MNIST network (conv-tanh-pool x2, dense-tanh)."""

    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            feats = nn.HybridSequential(prefix="")
            for ch in (20, 50):
                _cna(feats, ch, 5, norm=False, act="tanh", bias=True)
                feats.add(nn.MaxPool2D(2, 2))
            feats.add(nn.Flatten())
            feats.add(nn.Dense(500, activation="tanh"))
            self.features = feats
            self.output = nn.Dense(classes)

    def forward(self, x):
        return self.output(self.features(x))


def lenet(**kw):
    return LeNet(**_strip(kw))


# ---------------------------------------------------------------------------
# registry (model_zoo/__init__.py get_model parity)
# ---------------------------------------------------------------------------

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3, "lenet": lenet,
}


def get_model(name: str, **kwargs) -> HybridBlock:
    name = name.lower()
    if name not in _models:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_models)}")
    return _models[name](**kwargs)
