"""Pretrained weight store — parity with ``python/mxnet/gluon/model_zoo/model_store.py``.

Zero-egress: weights resolve from a local mirror (``MXTPU_REPO_DIR`` or
``~/.mxtpu/models``) in this framework's npz parameter format.
"""

from __future__ import annotations

import os


def get_model_file(name: str, root: str = "~/.mxtpu/models") -> str:
    fname = f"{name}.params"
    for base in [os.environ.get("MXTPU_REPO_DIR"), os.path.expanduser(root)]:
        if base:
            cand = os.path.join(base, fname)
            if os.path.exists(cand):
                return cand
    raise RuntimeError(
        f"pretrained weights {fname} not found locally (no network egress). "
        f"Place the file under $MXTPU_REPO_DIR or {root}, or use pretrained=False")


def load_pretrained(net, name: str, ctx=None, root: str = "~/.mxtpu/models"):
    net.load_parameters(get_model_file(name, root), ctx=ctx)


def purge(root: str = "~/.mxtpu/models"):
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
