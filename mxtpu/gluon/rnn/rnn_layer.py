"""Fused RNN layers — parity with ``python/mxnet/gluon/rnn/rnn_layer.py``
(RNN/LSTM/GRU: num_layers, bidirectional, dropout between layers, TNC/NTC layout,
begin_state). Backed by the fused ``rnn_scan`` op (lax.scan over MXU matmuls)."""

from __future__ import annotations

from typing import List, Optional

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size: int, num_layers: int, layout: str, dropout: float,
                 bidirectional: bool, input_size: int, mode: str,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, h = self._gates, hidden_size
        with self.name_scope():
            for layer in range(num_layers):
                for d, suffix in enumerate(["l", "r"][:self._dir]):
                    isz = input_size if layer == 0 else h * self._dir
                    setattr(self, f"{suffix}{layer}_i2h_weight", self.params.get(
                        f"{suffix}{layer}_i2h_weight", shape=(ng * h, isz),
                        init=i2h_weight_initializer, allow_deferred_init=True))
                    setattr(self, f"{suffix}{layer}_h2h_weight", self.params.get(
                        f"{suffix}{layer}_h2h_weight", shape=(ng * h, h),
                        init=h2h_weight_initializer))
                    setattr(self, f"{suffix}{layer}_i2h_bias", self.params.get(
                        f"{suffix}{layer}_i2h_bias", shape=(ng * h,),
                        init=i2h_bias_initializer))
                    setattr(self, f"{suffix}{layer}_h2h_bias", self.params.get(
                        f"{suffix}{layer}_h2h_bias", shape=(ng * h,),
                        init=h2h_bias_initializer))

    def state_info(self, batch_size: int = 0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        if self._mode == "lstm":
            return [{"shape": shape, "__layout__": "LNC"},
                    {"shape": shape, "__layout__": "LNC"}]
        return [{"shape": shape, "__layout__": "LNC"}]

    def begin_state(self, batch_size: int = 0, func=None, **kwargs) -> List[NDArray]:
        func = func or nd.zeros
        return [func(shape=info["shape"], **kwargs) for info in
                self.state_info(batch_size)]

    def forward(self, inputs, states=None):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        T, B = inputs.shape[0], inputs.shape[1]
        if self.params is not None:
            for layer in range(self._num_layers):
                for suffix in ["l", "r"][:self._dir]:
                    w = getattr(self, f"{suffix}{layer}_i2h_weight")
                    if w._data is None:
                        isz = inputs.shape[2] if layer == 0 else \
                            self._hidden_size * self._dir
                        w._finish_deferred_init(
                            (self._gates * self._hidden_size, isz))
        ret_states = states is not None
        if states is None:
            states = self.begin_state(B)
        elif not isinstance(states, (list, tuple)):
            states = [states]

        h_all = states[0]
        c_all = states[1] if self._mode == "lstm" else None
        out = inputs
        new_h, new_c = [], []
        for layer in range(self._num_layers):
            layer_outs = []
            for d, suffix in enumerate(["l", "r"][:self._dir]):
                idx = layer * self._dir + d
                h0 = h_all[idx]
                args = [out, h0]
                if self._mode == "lstm":
                    args.append(c_all[idx])
                args += [getattr(self, f"{suffix}{layer}_i2h_weight").data(),
                         getattr(self, f"{suffix}{layer}_i2h_bias").data(),
                         getattr(self, f"{suffix}{layer}_h2h_weight").data(),
                         getattr(self, f"{suffix}{layer}_h2h_bias").data()]
                res = nd.rnn_scan(*args, mode=self._mode, reverse=(d == 1))
                if self._mode == "lstm":
                    o, hT, cT = res
                    new_c.append(cT)
                else:
                    o, hT = res
                layer_outs.append(o)
                new_h.append(hT)
            out = layer_outs[0] if self._dir == 1 else nd.concat(*layer_outs, dim=2)
            if self._dropout > 0 and layer != self._num_layers - 1:
                out = nd.Dropout(out, p=self._dropout)

        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        out_states = [nd.stack(*new_h, axis=0)]
        if self._mode == "lstm":
            out_states.append(nd.stack(*new_c, axis=0))
        if ret_states:
            return out, out_states
        return out

    def __call__(self, inputs, states=None):
        # bypass HybridBlock's single-signature __call__ for the optional states arg
        if states is None:
            return super().__call__(inputs)
        return Block_call_with_states(self, inputs, states)


def Block_call_with_states(block, inputs, states):
    return block.forward(inputs, states)


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (relu/tanh) — rnn_layer.py RNN parity."""

    def __init__(self, hidden_size: int, num_layers: int = 1, activation: str = "relu",
                 layout: str = "TNC", dropout: float = 0.0, bidirectional: bool = False,
                 input_size: int = 0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, f"rnn_{activation}", **kwargs)


class LSTM(_RNNLayer):
    def __init__(self, hidden_size: int, num_layers: int = 1, layout: str = "TNC",
                 dropout: float = 0.0, bidirectional: bool = False,
                 input_size: int = 0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    def __init__(self, hidden_size: int, num_layers: int = 1, layout: str = "TNC",
                 dropout: float = 0.0, bidirectional: bool = False,
                 input_size: int = 0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "gru", **kwargs)
