"""gluon.rnn — recurrent layers and cells (parity with python/mxnet/gluon/rnn)."""

from .rnn_cell import (BidirectionalCell, DropoutCell, GRUCell, HybridRecurrentCell,
                       LSTMCell, ModifierCell, RecurrentCell, ResidualCell, RNNCell,
                       SequentialRNNCell, ZoneoutCell)
from .rnn_layer import GRU, LSTM, RNN
