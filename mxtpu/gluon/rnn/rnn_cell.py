"""RNN cells — parity with ``python/mxnet/gluon/rnn/rnn_cell.py``: RNNCell, LSTMCell,
GRUCell, SequentialRNNCell, DropoutCell, ZoneoutCell, ResidualCell, BidirectionalCell
+ ``unroll`` (explicit-step API used by BucketingModule workflows)."""

from __future__ import annotations

from typing import List, Optional

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False

    def state_info(self, batch_size: int = 0):
        raise NotImplementedError

    def begin_state(self, batch_size: int = 0, func=None, **kwargs):
        func = func or nd.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def reset(self):
        pass

    def __call__(self, inputs, states):
        return self.forward(inputs, states)

    def unroll(self, length: int, inputs, begin_state=None, layout: str = "NTC",
               merge_outputs: Optional[bool] = None, valid_length=None):
        """Explicit unroll (rnn_cell.py BaseRNNCell.unroll parity)."""
        axis = layout.find("T")
        if isinstance(inputs, NDArray):
            steps = nd.split(inputs, num_outputs=length, axis=axis, squeeze_axis=True) \
                if length > 1 else [inputs.squeeze(axis)]
        else:
            steps = list(inputs)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None else self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(steps[t], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=0)  # (T, N, C)
            masked = nd.SequenceMask(stacked, valid_length, use_sequence_length=True)
            outputs = [masked[t] for t in range(length)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states


class RNNCell(RecurrentCell):
    def __init__(self, hidden_size: int, activation: str = "tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size: int = 0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight",
                                              shape=(hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight",
                                              shape=(hidden_size, hidden_size),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                            init=i2h_bias_initializer)
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                            init=h2h_bias_initializer)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _finish(self, x):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init((self._hidden_size, x.shape[-1]))

    def forward(self, inputs, states):
        self._finish(inputs)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=self._hidden_size)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(),
                                self.h2h_bias.data(), num_hidden=self._hidden_size)
        out = nd.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(RecurrentCell):
    def __init__(self, hidden_size: int, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size: int = 0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        h = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(4 * h, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(4 * h, h),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * h,),
                                            init=i2h_bias_initializer)
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * h,),
                                            init=h2h_bias_initializer)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init(
                (4 * self._hidden_size, inputs.shape[-1]))
        h = self._hidden_size
        gates = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                  num_hidden=4 * h) + \
            nd.FullyConnected(states[0], self.h2h_weight.data(), self.h2h_bias.data(),
                              num_hidden=4 * h)
        i, f, g, o = nd.split(gates, num_outputs=4, axis=1)
        i, f, o = nd.sigmoid(i), nd.sigmoid(f), nd.sigmoid(o)
        g = nd.tanh(g)
        next_c = f * states[1] + i * g
        next_h = o * nd.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    def __init__(self, hidden_size: int, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size: int = 0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        h = hidden_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(3 * h, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(3 * h, h),
                                              init=h2h_weight_initializer)
            self.i2h_bias = self.params.get("i2h_bias", shape=(3 * h,),
                                            init=i2h_bias_initializer)
            self.h2h_bias = self.params.get("h2h_bias", shape=(3 * h,),
                                            init=h2h_bias_initializer)

    def state_info(self, batch_size: int = 0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def forward(self, inputs, states):
        if self.i2h_weight._data is None:
            self.i2h_weight._finish_deferred_init(
                (3 * self._hidden_size, inputs.shape[-1]))
        h = self._hidden_size
        ix = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                               num_hidden=3 * h)
        ih = nd.FullyConnected(states[0], self.h2h_weight.data(), self.h2h_bias.data(),
                               num_hidden=3 * h)
        ir, iz, inn = nd.split(ix, num_outputs=3, axis=1)
        hr, hz, hn = nd.split(ih, num_outputs=3, axis=1)
        r = nd.sigmoid(ir + hr)
        z = nd.sigmoid(iz + hz)
        n = nd.tanh(inn + r * hn)
        next_h = (1 - z) * n + z * states[0]
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size: int = 0):
        out = []
        for cell in self._children.values():
            out += cell.state_info(batch_size)
        return out

    def begin_state(self, batch_size: int = 0, **kwargs):
        out = []
        for cell in self._children.values():
            out += cell.begin_state(batch_size, **kwargs)
        return out

    def forward(self, inputs, states):
        next_states = []
        pos = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, st = cell(inputs, states[pos:pos + n])
            next_states += st
            pos += n
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    def __init__(self, rate: float, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size: int = 0):
        return []

    def forward(self, inputs, states):
        return nd.Dropout(inputs, p=self._rate, axes=self._axes), states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell: RecurrentCell):
        super().__init__()
        self.base_cell = base_cell

    def state_info(self, batch_size: int = 0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size: int = 0, **kwargs):
        return self.base_cell.begin_state(batch_size, **kwargs)

    def collect_params(self, select=None):
        return self.base_cell.collect_params(select)


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs: float = 0.0,
                 zoneout_states: float = 0.0):
        super().__init__(base_cell)
        self._zo, self._zs = zoneout_outputs, zoneout_states
        self._prev_output = None

    def reset(self):
        self._prev_output = None

    def forward(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        from ... import autograd
        if autograd.is_training():
            if self._zo > 0:
                prev = self._prev_output if self._prev_output is not None \
                    else nd.zeros_like(out)
                mask = nd.Dropout(nd.ones_like(out), p=self._zo)
                out = nd.where(mask, out, prev)
            if self._zs > 0:
                next_states = [
                    nd.where(nd.Dropout(nd.ones_like(ns), p=self._zs), ns, s)
                    for ns, s in zip(next_states, states)]
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    def forward(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        return out + inputs, next_states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix: str = "bi_"):
        super().__init__()
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size: int = 0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def begin_state(self, batch_size: int = 0, **kwargs):
        return (self._children["l_cell"].begin_state(batch_size, **kwargs)
                + self._children["r_cell"].begin_state(batch_size, **kwargs))

    def __call__(self, inputs, states):
        # REFERENCE PARITY, not a gap: the reference's BidirectionalCell also
        # raises on single-step (gluon/rnn/rnn_cell.py:1007 "Bidirectional
        # cannot be stepped. Please use unroll") — a bidirectional readout at
        # step t needs the t+1.. future, which a single step cannot see.
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        axis = layout.find("T")
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        if isinstance(inputs, NDArray):
            steps = nd.split(inputs, num_outputs=length, axis=axis, squeeze_axis=True)
        else:
            steps = list(inputs)
        batch = steps[0].shape[0]
        states = begin_state if begin_state is not None else self.begin_state(batch)
        nl = len(l_cell.state_info())
        l_states, r_states = states[:nl], states[nl:]
        l_outs, l_states = l_cell.unroll(length, steps, l_states, layout="NTC",
                                         merge_outputs=False)
        r_outs, r_states = r_cell.unroll(length, list(reversed(steps)), r_states,
                                         layout="NTC", merge_outputs=False)
        outs = [nd.concat(lo, ro, dim=1)
                for lo, ro in zip(l_outs, reversed(r_outs))]
        if merge_outputs:
            outs = nd.stack(*outs, axis=axis)
        return outs, l_states + r_states


class HybridRecurrentCell(RecurrentCell):
    pass
