"""Parameter / ParameterDict — parity with ``python/mxnet/gluon/parameter.py``
(deferred init, grad_req, save/load, Trainer handoff).

Re-design vs the reference: the reference replicates each Parameter's data across the
Context list (`list_ctx`) for multi-GPU data parallelism; on TPU replication/sharding
is a *compiler annotation* (pjit shardings carried by ``Parameter.sharding``), so a
Parameter owns ONE logical NDArray. ``list_data``/``list_grad`` exist for API parity
and return single-element lists.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import initializer as init_mod
from ..base import dtype_np
from ..context import Context, current_context
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray


class DeferredInitializationError(RuntimeError):
    pass


class Parameter:
    """A trainable tensor with deferred initialization.

    ``shape`` may contain 0 (unknown) dims; the owning layer completes it at first
    forward (`_finish_deferred_init`), matching the reference's shape-inference flow
    (parameter.py:561 _finish_deferred_init).
    """

    def __init__(self, name: str, grad_req: str = "write", shape=None, dtype="float32",
                 lr_mult: float = 1.0, wd_mult: float = 1.0, init=None,
                 allow_deferred_init: bool = False, differentiable: bool = True,
                 stype: str = "default", grad_stype: str = "default"):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.grad_req = grad_req if differentiable else "null"
        self.stype = stype
        self._data: Optional[NDArray] = None
        self._deferred_init: Optional[tuple] = None  # (init, ctx)
        self.sharding = None  # optional pjit PartitionSpec (TPU-first extension)

    # -- init --------------------------------------------------------------
    def _shape_complete(self) -> bool:
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx: Optional[Context] = None,
                   default_init=None, force_reinit: bool = False):
        if self._data is not None and not force_reinit:
            return
        chosen = init or self.init or default_init or init_mod.Uniform()
        if not self._shape_complete():
            if not self.allow_deferred_init:
                raise ValueError(
                    f"Parameter {self.name}: shape {self.shape} incomplete and "
                    "deferred init not allowed")
            self._deferred_init = (chosen, ctx)
            return
        self._init_impl(chosen, ctx)

    def _init_impl(self, chosen, ctx):
        if self._data is not None and self._data.shape == tuple(self.shape):
            # force_reinit: keep the SAME handle so hybridized CachedOps (which
            # captured it) see the new values
            arr = self._data
            arr._set_data(jnp.zeros(self.shape, dtype_np(self.dtype)))
        else:
            arr = NDArray(jnp.zeros(self.shape, dtype_np(self.dtype)), ctx=ctx)
        init_mod.create(chosen).init_array(self.name, arr)
        self._data = arr
        self._deferred_init = None
        if self.grad_req != "null":
            self._data.attach_grad(self.grad_req)

    def _finish_deferred_init(self, shape: Tuple[int, ...]):
        """Complete unknown dims from the first forward's observed shape."""
        if self.shape is not None:
            merged = tuple(o if o > 0 else n for o, n in zip(self.shape, shape))
        else:
            merged = tuple(shape)
        self.shape = merged
        if self._deferred_init is not None:
            chosen, ctx = self._deferred_init
            self._init_impl(chosen, ctx)

    # -- access ------------------------------------------------------------
    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init is not None or not self._shape_complete():
                raise DeferredInitializationError(
                    f"Parameter {self.name} deferred (shape {self.shape}); run a "
                    "forward pass or complete the shape first")
            raise RuntimeError(
                f"Parameter {self.name} has not been initialized; call "
                ".initialize() on the block or parameter first")

    def data(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized()
        return self._data

    def list_data(self) -> List[NDArray]:
        return [self.data()]

    def grad(self, ctx: Optional[Context] = None) -> NDArray:
        self._check_initialized()
        if self._data._grad is None:
            raise RuntimeError(f"Parameter {self.name} grad_req='null' — no gradient")
        return self._data._grad

    def list_grad(self) -> List[NDArray]:
        return [self.grad()]

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        return [self._data.context]

    def set_data(self, data):
        if self._data is None:
            if self._deferred_init is not None:
                self.shape = tuple(data.shape)
                chosen, ctx = self._deferred_init
                self._init_impl(chosen, ctx)
            else:
                raise RuntimeError(f"Parameter {self.name} not initialized")
        src = data if isinstance(data, NDArray) else NDArray(data)
        self._data._set_data(src.data.astype(self._data.dtype).reshape(self._data.shape))

    def zero_grad(self):
        if self._data is None or self._data._grad is None:
            return
        g = self._data._grad
        if getattr(g, "stype", "default") == "row_sparse":
            from ..ndarray import sparse as _sparse
            self._data._grad = _sparse.zeros("row_sparse", g.shape, dtype=g.dtype)
        else:
            g._set_data(jnp.zeros_like(g.data))

    def reset_ctx(self, ctx):
        pass  # single logical device; sharding handles placement

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data._set_data(self._data.data.astype(dtype_np(dtype)))

    def var(self):
        raise NotImplementedError(
            "symbolic var() has no equivalent — hybridize traces the python forward")

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-trainable constant parameter (gluon.Constant parity)."""

    def __init__(self, name: str, value):
        value = value if isinstance(value, NDArray) else _nd.array(value)
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=str(value.dtype))
        self._value = value
        self.init = init_mod.Constant(0)

    def _init_impl(self, chosen, ctx):
        self._data = NDArray(self._value.data, ctx=ctx)
        self._deferred_init = None


class ParameterDict:
    """Ordered name→Parameter mapping with prefix sharing (parameter.py:654)."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"] = None):
        self.prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self) -> Iterator[str]:
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, name: str) -> Parameter:
        return self._params[name]

    def __contains__(self, name: str) -> bool:
        return name in self._params

    def get(self, name: str, **kwargs) -> Parameter:
        """Create-or-retrieve by relative name (prefix applied), reference semantics."""
        full = self.prefix + name
        if full in self._params:
            param = self._params[full]
            for k, v in kwargs.items():
                if v is not None and getattr(param, k, None) in (None, 0):
                    setattr(param, k, v)
            return param
        if self._shared is not None and full in self._shared:
            param = self._shared[full]
        else:
            param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        full = self.prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other: "ParameterDict"):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose: bool = False,
                   force_reinit: bool = False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name: str, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename: str, strip_prefix: str = ""):
        arrays = {}
        for name, p in self.items():
            if p._data is None:
                continue
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            arrays[key] = p.data()
        _nd.save(filename, arrays)

    def load(self, filename: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = ""):
        loaded = _nd.load(filename)
        if isinstance(loaded, list):
            raise ValueError("expected a dict-style parameter file")
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise ValueError(f"parameter {name} missing from {filename}")
        for name, arr in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise ValueError(f"parameter {name} in file not in ParameterDict")
            p = self._params[name]
            if p._data is None:
                p.shape = tuple(arr.shape)
                p._deferred_init = p._deferred_init or (p.init, None)
                chosen, ctx_ = p._deferred_init
                p._init_impl(chosen or init_mod.Uniform(), ctx_)
            p.set_data(arr)

    def __repr__(self):
        lines = "\n".join(f"  {p!r}" for p in self.values())
        return f"ParameterDict(prefix={self.prefix!r}\n{lines}\n)"
