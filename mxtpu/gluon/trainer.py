"""Trainer — parity with ``python/mxnet/gluon/trainer.py`` (kvstore-backed optimizer
driver: allreduce_grads → update, save/load_states, gradient compression hookup)."""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from .. import kvstore as kv_mod
from .. import optimizer as opt_mod
from ..ndarray.ndarray import NDArray
from ..step_cache import (build_update_all, cache_stats, donation_supported,
                          optimizer_fingerprint, unique_buffers)
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params: Optional[dict] = None,
                 kvstore: Union[str, "kv_mod.KVStore", None] = "device",
                 compression_params: Optional[dict] = None,
                 update_on_kvstore: Optional[bool] = None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        self._params: List[Parameter] = [p for p in params if p.grad_req != "null"]
        self._all_params = list(params)
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._optimizer = opt_mod.create(optimizer, **optimizer_params) \
            if isinstance(optimizer, str) else optimizer
        self._optimizer.param_dict = {i: p for i, p in enumerate(self._params)}
        self._states = [None] * len(self._params)
        self._kv_type = kvstore
        self._kvstore: Optional[kv_mod.KVStore] = None
        self._update_on_kvstore = update_on_kvstore
        self._compression_params = compression_params
        self._kv_initialized = False
        # ZeRO-1 slots (owned by the fused StepExecutor when zero_requested():
        # per-BUCKET dp-sharded flat arrays instead of per-param tuples);
        # _zero_restore stages a checkpointed state until the layout exists
        self._zero_layout = None
        self._zero_states: List = []
        self._zero_residuals: List = []
        self._zero_restore = None
        # bulked update: ONE jitted program applying the optimizer to every
        # parameter (vs one dispatch per param), cached by signature
        self._bulk_cache: Dict[tuple, object] = {}
        self._bulk_stats = cache_stats("trainer_update")

    # -- kvstore wiring ----------------------------------------------------
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        if self._kv_type is None:
            self._kvstore = None
        else:
            kvs = self._kv_type if isinstance(self._kv_type, kv_mod.KVStore) \
                else kv_mod.create(self._kv_type)
            self._kvstore = kvs
            if self._compression_params:
                kvs.set_gradient_compression(self._compression_params)
            update_on_kv = self._update_on_kvstore
            if update_on_kv is None:
                update_on_kv = kvs.type.startswith("dist")
            self._update_on_kv = update_on_kv
            for i, p in enumerate(self._params):
                kvs.init(i, p.data())
            if update_on_kv:
                kvs.set_optimizer(self._optimizer)
        self._kv_initialized = True

    def zero_requested(self) -> bool:
        """True when this trainer's kvstore type selects the ZeRO sharded
        gradient/update path (the fused step's dataflow: bucketed
        reduce-scatter → 1/N-sharded optimizer slots → all-gather;
        parallel/zero.py). The stage is a separate knob —
        ``MXTPU_ZERO_STAGE=1|2|3`` (parallel/fsdp.py) escalates from sharded
        slots (1) to reduce-scattered grad accumulators (2) to 1/N-resident
        fsdp-sharded parameters (3). The reference's ``device``/``dist_sync``
        types map here — exactly the types whose KVStore sharded state across
        devices/servers. ``local`` kvstores, an explicit
        ``update_on_kvstore=True`` (server-side updates), ``MXTPU_ZERO=0``,
        and non-elementwise optimizers all keep the replicated-psum path."""
        from ..parallel import zero as zero_mod
        self._init_kvstore()
        if self._kvstore is None or self._update_on_kvstore is True:
            return False
        if self._kvstore.type not in ("tpu", "dist", "dist_sync",
                                      "dist_device_sync"):
            return False
        return zero_mod.zero_enabled() \
            and zero_mod.supports_zero(self._optimizer)

    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr: float):
        self._optimizer.set_learning_rate(lr)

    @property
    def optimizer(self):
        return self._optimizer

    # -- the step ----------------------------------------------------------
    def step(self, batch_size: int, ignore_stale_grad: bool = False):
        """allreduce (kvstore) + optimizer update; grads rescaled by 1/batch_size
        (trainer.py step parity)."""
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self.allreduce_grads()
        self.update(batch_size, ignore_stale_grad, _skip_allreduce=True)

    def allreduce_grads(self):
        self._init_kvstore()
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            if self._update_on_kv:
                continue  # push+pull handled in update for update_on_kvstore=False
            # local kvstore without server updater: push/pull is a no-op reduce for
            # a single logical device — grads already aggregated by XLA collectives.

    # -- bulked update (engine-op-bulking parity for the optimizer pass) ----
    def _can_bulk_update(self) -> bool:
        from .. import engine
        if engine.bulk_size() == 0 or not self._params:
            return False
        if self._kvstore is not None and getattr(self, "_update_on_kv", False):
            return False
        opt = self._optimizer
        if getattr(opt, "multi_precision", False):
            return False
        for p in self._params:
            if p._data is None:
                return False
            g = p._data._grad
            if g is None or getattr(g, "stype", "default") != "default":
                return False    # stale or row-sparse grads: per-param path
        return True

    def _bulk_update(self):
        """Apply the optimizer to ALL params in one compiled program — the
        dispatch-amortized sibling of the reference's op bulking, sharing
        ``step_cache.build_update_all`` with the fused training step."""
        import jax.numpy as jnp

        opt = self._optimizer
        params = self._params
        donate = donation_supported()
        for i, p in enumerate(params):
            if self._states[i] is None:
                st = opt.create_state_multi_precision(i, p.data())
                self._states[i] = unique_buffers(st) if donate else tuple(st)

        def asig(v):
            return (tuple(v.shape), str(v.dtype),
                    getattr(v, "sharding", None))

        sig = (tuple(asig(p._data._data) for p in params),
               tuple(asig(p._data._grad._data) for p in params),
               tuple(tuple(asig(s) for s in (st or ()))
                     for st in self._states),
               optimizer_fingerprint(opt))
        entry = self._bulk_cache.get(sig)
        if entry is None:
            self._bulk_stats.miss()
            import jax
            update_all = build_update_all(
                opt,
                [getattr(p, "lr_mult", 1.0) * opt.lr_mult.get(i, 1.0)
                 for i, p in enumerate(params)],
                [getattr(p, "wd_mult", 1.0) * opt.wd_mult.get(i, 1.0)
                 for i, p in enumerate(params)])
            entry = self._bulk_cache[sig] = jax.jit(
                update_all, donate_argnums=(0, 2) if donate else ())
        else:
            self._bulk_stats.hit()

        t = max([opt._index_update_count.get(i, 0)
                 for i in range(len(params))] or [0]) + 1
        # eager parity: _update_count runs before _get_lr, so the scheduler
        # sees the post-increment num_update
        lr = jnp.float32(opt.lr_scheduler(max(opt.num_update, t))
                         if opt.lr_scheduler else opt.lr)
        wd = jnp.float32(opt.wd)
        rescale = jnp.float32(opt.rescale_grad)
        clip = jnp.float32(opt.clip_gradient
                           if opt.clip_gradient is not None else 0.0)
        new_params, new_states = entry(
            [p._data._data for p in params],
            [p._data._grad._data for p in params],
            list(self._states), lr, wd, rescale, clip, t)
        for p, w in zip(params, new_params):
            p._data._set_data(w)
        self._states = list(new_states)
        for i in range(len(params)):
            opt._index_update_count[i] = t
        opt.num_update = max(opt.num_update, t)

    def update(self, batch_size: int, ignore_stale_grad: bool = False,
               _skip_allreduce: bool = False):
        self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._can_bulk_update():
            self._bulk_update()
            return
        for i, p in enumerate(self._params):
            if p._data is None:
                continue
            grad = p._data._grad
            if grad is None:
                if ignore_stale_grad:
                    continue
                raise RuntimeError(f"Parameter {p.name} has no gradient; run "
                                   "backward() inside autograd.record() first")
            if self._kvstore is not None and self._update_on_kv:
                self._kvstore.push(i, grad)
                self._kvstore.pull(i, p.data())
            else:
                if self._states[i] is None:
                    self._states[i] = self._optimizer.create_state_multi_precision(
                        i, p.data())
                self._states[i] = self._optimizer.update(i, p.data(), grad,
                                                         self._states[i])

    # -- state io ----------------------------------------------------------
    def states_dict(self) -> dict:
        """Host-side optimizer state (slots + update counters) as a plain
        picklable dict — the Trainer half of a checkpoint snapshot."""
        import jax
        import numpy as np
        self._init_kvstore()
        blob = {i: [np.asarray(jax.device_get(x)) for x in (s or ())]
                for i, s in enumerate(self._states)}
        return {"states": blob, "num_update": self._optimizer.num_update,
                "counts": dict(self._optimizer._index_update_count)}

    def load_states_dict(self, data: dict):
        import jax.numpy as jnp
        self._init_kvstore()
        self._states = [tuple(jnp.asarray(x) for x in data["states"].get(i, ()))
                        or None for i in range(len(self._params))]
        self._optimizer.num_update = data["num_update"]
        self._optimizer._index_update_count = dict(data["counts"])

    def save_states(self, fname: str):
        """Atomic (tempfile + fsync + rename via checkpoint.atomic_io): a
        kill mid-save leaves the previous states file, never a torn one."""
        self._init_kvstore()
        if self._kvstore is not None and self._update_on_kv:
            self._kvstore.save_optimizer_states(fname)
            return
        import pickle
        from ..checkpoint import atomic_io
        atomic_io.atomic_write(
            fname, lambda f: pickle.dump(self.states_dict(), f))

    def load_states(self, fname: str):
        self._init_kvstore()
        if self._kvstore is not None and self._update_on_kv:
            self._kvstore.load_optimizer_states(fname)
            return
        import pickle
        with open(fname, "rb") as f:
            self.load_states_dict(pickle.load(f))
