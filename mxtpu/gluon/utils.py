"""Gluon utilities — parity with ``python/mxnet/gluon/utils.py``: split_data,
split_and_load, clip_global_norm, check_sha1, download (gated: zero-egress)."""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Sequence

import jax.numpy as jnp

from .. import ndarray as nd
from ..context import Context
from ..ndarray.ndarray import NDArray


def split_data(data: NDArray, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List[NDArray]:
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"cannot evenly split axis {batch_axis} of size {size} into {num_slice}")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list: Sequence[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List[NDArray]:
    """Reference: slice a batch across GPUs. On TPU, prefer sharded arrays
    (mxtpu.parallel.shard_batch) — this exists for API/migration parity."""
    data = data if isinstance(data, NDArray) else nd.array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def clip_global_norm(arrays: Sequence[NDArray], max_norm: float) -> float:
    """Rescale arrays in place so their joint L2 norm ≤ max_norm (utils.py parity)."""
    total = 0.0
    sq = [jnp.sum(jnp.square(a.data)) for a in arrays]
    total = jnp.sqrt(sum(sq))
    scale = jnp.minimum(1.0, max_norm / (total + 1e-12))
    for a in arrays:
        a._set_data(a.data * scale.astype(a.data.dtype))
    return float(total)


def check_sha1(filename: str, sha1_hash: str) -> bool:
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            sha1.update(chunk)
    return sha1.hexdigest() == sha1_hash


def download(url: str, path: Optional[str] = None, overwrite: bool = False,
             sha1_hash: Optional[str] = None) -> str:
    """Model-zoo download shim. This environment is zero-egress; honor a local
    mirror via MXTPU_REPO_DIR, else raise with guidance."""
    fname = url.split("/")[-1]
    repo = os.environ.get("MXTPU_REPO_DIR")
    if repo:
        cand = os.path.join(repo, fname)
        if os.path.exists(cand):
            return cand
    raise RuntimeError(
        f"cannot download {url}: no network egress. Set MXTPU_REPO_DIR to a local "
        "mirror directory containing the file, or pass pretrained=False")
