"""Gluon-equivalent imperative/hybrid module system (parity with python/mxnet/gluon)."""

from . import loss
from . import nn
from . import rnn
from . import utils
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict
from .trainer import Trainer

from . import data  # noqa: E402
from . import model_zoo  # noqa: E402
from . import contrib  # noqa: E402
