"""gluon.data — datasets, samplers, DataLoader (parity with python/mxnet/gluon/data)."""

from . import vision
from .dataloader import DataLoader
from .dataset import ArrayDataset, Dataset, RecordFileDataset, SimpleDataset
from .sampler import (BatchSampler, IntervalSampler, RandomSampler, Sampler,
                      SequentialSampler)
