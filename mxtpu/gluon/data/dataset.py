"""Datasets — parity with ``python/mxnet/gluon/data/dataset.py``."""

from __future__ import annotations

import os
from typing import Callable, List, Sequence

import numpy as np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn: Callable, lazy: bool = True) -> "Dataset":
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn: Callable, lazy: bool = True) -> "Dataset":
        def base_fn(x, *args):
            if args:
                return (fn(x),) + args
            return fn(x)
        return self.transform(base_fn, lazy)

    def filter(self, fn: Callable) -> "Dataset":
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def take(self, count: int) -> "Dataset":
        return SimpleDataset([self[i] for i in range(min(count, len(self)))])


class _LazyTransformDataset(Dataset):
    def __init__(self, data: Dataset, fn: Callable):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class SimpleDataset(Dataset):
    def __init__(self, data: Sequence):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class ArrayDataset(Dataset):
    """Zip of arrays/datasets (dataset.py ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for a in args:
            assert len(a) == self._length, "all arrays must have the same length"
            if isinstance(a, NDArray):
                a = a.asnumpy()
            self._data.append(a)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (dataset.py RecordFileDataset)."""

    def __init__(self, filename: str):
        from ... import recordio
        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
