"""DataLoader — parity with ``python/mxnet/gluon/data/dataloader.py``.

The reference forks worker processes and rebuilds NDArrays over POSIX shared memory
(ForkingPickler + CPUSharedStorageManager, dataloader.py:26-96, storage.cc:96). Here
workers run in a **thread pool over numpy** (decode/augment release the GIL via
numpy/PIL) and the batch is device_put once per batch — host→TPU transfer is the only
device interaction, so there is no shared-memory tensor protocol to rebuild. A
``prefetch`` window of in-flight batches double-buffers the pipeline like the
reference's PrefetcherIter.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples into a batch (dataloader.py default_batchify_fn parity)."""
    if isinstance(data[0], tuple):
        return tuple(default_batchify_fn([d[i] for d in data])
                     for i in range(len(data[0])))
    if isinstance(data[0], NDArray):
        return nd.array(np.stack([d.asnumpy() for d in data]))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return nd.array(arr)


class DataLoader:
    """``ctx``/``sharding`` turn on the device boundary: when a target
    device, mesh, or ``NamedSharding`` is given, ``__iter__`` routes batches
    through a ``device_feed.DeviceFeed`` — a producer thread keeps the next
    ``feed_depth`` batches resident on-device (sharded, committed,
    non-blocking ``device_put``) so the training step never waits on the
    host. Stall/transfer accounting: ``profiler.get_feed_stats()``."""

    def __init__(self, dataset: Dataset, batch_size: Optional[int] = None,
                 shuffle: bool = False, sampler: Optional[Sampler] = None,
                 last_batch: Optional[str] = None,
                 batch_sampler: Optional[BatchSampler] = None,
                 batchify_fn: Optional[Callable] = None, num_workers: int = 0,
                 prefetch: Optional[int] = None, ctx=None, sharding=None,
                 feed_depth: Optional[int] = None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must be False with an explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(1, prefetch if prefetch is not None
                             else 2 * max(1, self._num_workers))
        self._placement = ctx if ctx is not None else sharding
        self._feed_depth = feed_depth

    def _load_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _batches(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._load_batch(indices)
            return
        with ThreadPoolExecutor(self._num_workers) as pool:
            futures = []
            it = iter(self._batch_sampler)
            try:
                for _ in range(self._prefetch):
                    futures.append(pool.submit(self._load_batch, next(it)))
            except StopIteration:
                pass
            while futures:
                batch = futures.pop(0).result()
                try:
                    futures.append(pool.submit(self._load_batch, next(it)))
                except StopIteration:
                    pass
                yield batch

    def __iter__(self):
        if self._placement is None:
            yield from self._batches()
            return
        from ...device_feed import DeviceFeed
        feed = DeviceFeed(self._batches(), depth=self._feed_depth,
                          placement=self._placement)
        try:
            yield from feed
        finally:
            feed.close()  # early break: stop the producer, drop its queue

    def __len__(self):
        return len(self._batch_sampler)
