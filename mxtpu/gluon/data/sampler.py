"""Samplers — parity with ``python/mxnet/gluon/data/sampler.py``."""

from __future__ import annotations

import numpy as np


class Sampler:
    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(range(self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length: int):
        self._length = length

    def __iter__(self):
        return iter(np.random.permutation(self._length).tolist())

    def __len__(self):
        return self._length


class BatchSampler(Sampler):
    """last_batch ∈ {keep, discard, rollover} (sampler.py BatchSampler)."""

    def __init__(self, sampler: Sampler, batch_size: int, last_batch: str = "keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch
            elif self._last_batch != "discard":
                raise ValueError(f"unknown last_batch {self._last_batch!r}")

    def __len__(self):
        n = len(self._sampler)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        return (n + len(self._prev)) // self._batch_size


class IntervalSampler(Sampler):
    def __init__(self, length: int, interval: int, rollover: bool = True):
        self._length, self._interval, self._rollover = length, interval, rollover

    def __iter__(self):
        for start in (range(self._interval) if self._rollover else [0]):
            yield from range(start, self._length, self._interval)

    def __len__(self):
        return self._length if self._rollover else \
            (self._length + self._interval - 1) // self._interval
