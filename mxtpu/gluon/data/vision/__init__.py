from . import transforms
from .datasets import (CIFAR10, CIFAR100, FashionMNIST, ImageFolderDataset,
                       ImageRecordDataset, MNIST)
