"""Vision datasets — parity with ``python/mxnet/gluon/data/vision/datasets.py``
(MNIST, FashionMNIST, CIFAR10/100, ImageRecordDataset, ImageFolderDataset).

Zero-egress environment: dataset files must already exist under ``root`` (or a
synthetic fallback is available for tests via ``synthetic=True``).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from .... import ndarray as nd
from ..dataset import ArrayDataset, Dataset


class _DownloadedDataset(Dataset):
    def __init__(self, root: str, transform: Optional[Callable]):
        self._root = os.path.expanduser(root)
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from the standard IDX files (train-images-idx3-ubyte[.gz] etc.)."""

    def __init__(self, root: str = "~/.mxtpu/datasets/mnist", train: bool = True,
                 transform: Optional[Callable] = None, synthetic: bool = False):
        self._train = train
        self._synthetic = synthetic
        super().__init__(root, transform)

    def _get_data(self):
        prefix = "train" if self._train else "t10k"
        img = os.path.join(self._root, f"{prefix}-images-idx3-ubyte")
        lbl = os.path.join(self._root, f"{prefix}-labels-idx1-ubyte")
        if not (os.path.exists(img) or os.path.exists(img + ".gz")):
            if self._synthetic:
                rs = np.random.RandomState(42)
                n = 1024 if self._train else 256
                self._data = rs.randint(0, 255, (n, 28, 28, 1)).astype(np.uint8)
                self._label = rs.randint(0, 10, (n,)).astype(np.int32)
                return
            raise RuntimeError(
                f"MNIST files not found under {self._root} (no network egress; "
                "place the IDX files there or pass synthetic=True)")
        self._data = _read_idx_images(img)
        self._label = _read_idx_labels(lbl)


class FashionMNIST(MNIST):
    def __init__(self, root: str = "~/.mxtpu/datasets/fashion-mnist", **kwargs):
        super().__init__(root=root, **kwargs)


def _maybe_gz(path: str):
    if os.path.exists(path):
        return open(path, "rb")
    return gzip.open(path + ".gz", "rb")


def _read_idx_images(path: str) -> np.ndarray:
    with _maybe_gz(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, rows, cols, 1)


def _read_idx_labels(path: str) -> np.ndarray:
    with _maybe_gz(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.astype(np.int32)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root: str = "~/.mxtpu/datasets/cifar10", train: bool = True,
                 transform: Optional[Callable] = None, synthetic: bool = False):
        self._train = train
        self._synthetic = synthetic
        super().__init__(root, transform)

    def _get_data(self):
        batch_dir = os.path.join(self._root, "cifar-10-batches-py")
        if not os.path.isdir(batch_dir):
            if self._synthetic:
                rs = np.random.RandomState(0)
                n = 1024 if self._train else 256
                self._data = rs.randint(0, 255, (n, 32, 32, 3)).astype(np.uint8)
                self._label = rs.randint(0, 10, (n,)).astype(np.int32)
                return
            raise RuntimeError(f"CIFAR-10 python batches not found in {self._root}")
        files = [f"data_batch_{i}" for i in range(1, 6)] if self._train \
            else ["test_batch"]
        data, labels = [], []
        for fn in files:
            with open(os.path.join(batch_dir, fn), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            data.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.extend(d[b"labels"])
        self._data = np.concatenate(data)
        self._label = np.asarray(labels, np.int32)


class CIFAR100(CIFAR10):
    def __init__(self, root: str = "~/.mxtpu/datasets/cifar100", fine_label=True,
                 **kwargs):
        self._fine = fine_label
        super().__init__(root=root, **kwargs)


class ImageRecordDataset(Dataset):
    """Images from a RecordIO pack (datasets.py ImageRecordDataset)."""

    def __init__(self, filename: str, flag: int = 1,
                 transform: Optional[Callable] = None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from .... import recordio
        from .... import image
        raw = self._record[idx]
        header, img_bytes = recordio.unpack(raw)
        img = image.imdecode(img_bytes, flag=self._flag)
        label = np.float32(header.label) if np.isscalar(header.label) \
            else np.asarray(header.label, np.float32)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """folder/<class>/<image> layout (datasets.py ImageFolderDataset)."""

    def __init__(self, root: str, flag: int = 1,
                 transform: Optional[Callable] = None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = {".jpg", ".jpeg", ".png", ".bmp"}
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fn in sorted(os.listdir(path)):
                if os.path.splitext(fn)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, fn), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image
        path, label = self.items[idx]
        with open(path, "rb") as f:
            img = image.imdecode(f.read(), flag=self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, np.float32(label)
