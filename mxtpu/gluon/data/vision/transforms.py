"""Vision transforms — parity with ``python/mxnet/gluon/data/vision/transforms.py``:
Compose, Cast, ToTensor, Normalize, RandomResizedCrop, CenterCrop, Resize,
RandomFlipLeftRight/TopBottom, RandomBrightness/Contrast/Saturation/Hue/ColorJitter,
RandomLighting. Operate on HWC uint8/float numpy or NDArray (host-side, like the
reference's CPU augmentation pipeline)."""

from __future__ import annotations

import random as pyrandom
from typing import Optional, Sequence

import numpy as np

from .... import ndarray as nd
from ....ndarray.ndarray import NDArray
from ...block import Block


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else np.asarray(x)


class Compose(Block):
    def __init__(self, transforms):
        super().__init__()
        self._transforms = transforms

    def forward(self, x):
        for t in self._transforms:
            x = t(x)
        return x


class Cast(Block):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return nd.array(_to_np(x).astype(self._dtype))


class ToTensor(Block):
    """HWC uint8 [0,255] → CHW float32 [0,1] (transforms.py ToTensor)."""

    def forward(self, x):
        arr = _to_np(x).astype(np.float32) / 255.0
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        elif arr.ndim == 4:
            arr = arr.transpose(0, 3, 1, 2)
        return nd.array(arr)


class Normalize(Block):
    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32)
        self._std = np.asarray(std, np.float32)

    def forward(self, x):
        arr = _to_np(x)
        mean = self._mean.reshape(-1, 1, 1) if self._mean.ndim else self._mean
        std = self._std.reshape(-1, 1, 1) if self._std.ndim else self._std
        return nd.array((arr - mean) / std)


class Resize(Block):
    def __init__(self, size, keep_ratio: bool = False, interpolation: int = 1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        from .... import image
        return image.imresize(x, self._size[0], self._size[1])


class CenterCrop(Block):
    def __init__(self, size):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        arr = _to_np(x)
        h, w = arr.shape[:2]
        cw, ch = self._size
        x0 = max(0, (w - cw) // 2)
        y0 = max(0, (h - ch) // 2)
        return nd.array(arr[y0:y0 + ch, x0:x0 + cw])


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation: int = 1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale, self._ratio = scale, ratio

    def forward(self, x):
        from .... import image
        arr = _to_np(x)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self._scale)
            ar = pyrandom.uniform(*self._ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                x0 = pyrandom.randint(0, w - cw)
                y0 = pyrandom.randint(0, h - ch)
                crop = arr[y0:y0 + ch, x0:x0 + cw]
                return image.imresize(nd.array(crop), self._size[0], self._size[1])
        return CenterCrop(self._size)(nd.array(arr))


class RandomFlipLeftRight(Block):
    def forward(self, x):
        arr = _to_np(x)
        if pyrandom.random() < 0.5:
            arr = arr[:, ::-1]
        return nd.array(np.ascontiguousarray(arr))


class RandomFlipTopBottom(Block):
    def forward(self, x):
        arr = _to_np(x)
        if pyrandom.random() < 0.5:
            arr = arr[::-1]
        return nd.array(np.ascontiguousarray(arr))


class RandomBrightness(Block):
    def __init__(self, brightness: float):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        f = 1.0 + pyrandom.uniform(-self._b, self._b)
        return nd.array(np.clip(arr * f, 0, 255))


class RandomContrast(Block):
    def __init__(self, contrast: float):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        f = 1.0 + pyrandom.uniform(-self._c, self._c)
        gray = arr.mean()
        return nd.array(np.clip(gray + (arr - gray) * f, 0, 255))


class RandomSaturation(Block):
    def __init__(self, saturation: float):
        super().__init__()
        self._s = saturation

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        f = 1.0 + pyrandom.uniform(-self._s, self._s)
        gray = arr.mean(axis=-1, keepdims=True)
        return nd.array(np.clip(gray + (arr - gray) * f, 0, 255))


class RandomHue(Block):
    def __init__(self, hue: float):
        super().__init__()
        self._h = hue

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        f = pyrandom.uniform(-self._h, self._h)
        # cheap hue rotation approximation in RGB (reference uses HSL roundtrip)
        u = np.cos(f * np.pi)
        w = np.sin(f * np.pi)
        t = np.array([[0.299, 0.587, 0.114],
                      [0.299, 0.587, 0.114],
                      [0.299, 0.587, 0.114]], np.float32) + \
            u * np.array([[0.701, -0.587, -0.114],
                          [-0.299, 0.413, -0.114],
                          [-0.299, -0.587, 0.886]], np.float32) + \
            w * np.array([[0.168, 0.330, -0.497],
                          [-0.328, 0.035, 0.292],
                          [1.250, -1.050, -0.203]], np.float32)
        return nd.array(np.clip(arr @ t.T, 0, 255))


class RandomColorJitter(Block):
    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        super().__init__()
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def forward(self, x):
        ts = list(self._ts)
        pyrandom.shuffle(ts)
        for t in ts:
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA lighting noise (transforms.py RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha: float):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        arr = _to_np(x).astype(np.float32)
        alpha = np.random.normal(0, self._alpha, 3).astype(np.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return nd.array(np.clip(arr + rgb, 0, 255))
