"""Contrib RNN cells — VariationalDropoutCell (gluon.contrib.rnn parity):
one dropout mask per sequence (variational), applied to inputs/states/outputs."""

from __future__ import annotations

from typing import Optional

from ... import autograd
from ... import ndarray as nd
from ..rnn.rnn_cell import ModifierCell


class VariationalDropoutCell(ModifierCell):
    def __init__(self, base_cell, drop_inputs: float = 0.0, drop_states: float = 0.0,
                 drop_outputs: float = 0.0):
        super().__init__(base_cell)
        self._di, self._ds, self._do = drop_inputs, drop_states, drop_outputs
        self.reset()

    def reset(self):
        self._mask_in = None
        self._mask_state = None
        self._mask_out = None
        if hasattr(self.base_cell, "reset"):
            self.base_cell.reset()

    def _mask(self, cache_attr, rate, arr):
        if rate == 0.0 or not autograd.is_training():
            return arr
        mask = getattr(self, cache_attr)
        if mask is None or mask.shape != arr.shape:
            mask = nd.Dropout(nd.ones_like(arr), p=rate)
            setattr(self, cache_attr, mask)
        return arr * mask

    def forward(self, inputs, states):
        inputs = self._mask("_mask_in", self._di, inputs)
        if self._ds:
            # reference masks only states[0] (the hidden state, not LSTM cell
            # memory — gluon/contrib/rnn/rnn_cell.py)
            states = [self._mask("_mask_state", self._ds, states[0])] + \
                list(states[1:])
        out, next_states = self.base_cell(inputs, states)
        out = self._mask("_mask_out", self._do, out)
        return out, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        return super().unroll(length, inputs, begin_state, layout, merge_outputs,
                              valid_length)
