"""Contrib layers.

``SyncBatchNorm`` — parity with the reference's cross-GPU synced BN
(src/operator/contrib/sync_batch_norm-inl.h:55-93, gluon.contrib.SyncBatchNorm):
the reference synchronizes batch statistics across devices with a key-matched
barrier + CPU reduction; here the data-parallel dimension is a mesh axis, so the
stat sync is ONE ``lax.pmean`` inside the sharded program — XLA rides ICI and
overlaps it with the surrounding compute.

``MultiHeadAttention`` — flash-attention-backed block (TPU-first addition; the
reference has no attention layer).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ... import autograd
from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from ..block import HybridBlock
from ..nn.basic_layers import BatchNorm, Dense


def _layout_constrain(x: NDArray, entry: str) -> NDArray:
    """SpecLayout activation constraint (identity unless a composed-mesh
    step is tracing under ``parallel.fsdp.layout_scope``)."""
    from ...parallel import fsdp as _fsdp   # lazy: parallel imports gluon
    raw = _fsdp.constrain(x.data, entry)
    return x if raw is x.data else NDArray(raw)


class SyncBatchNorm(BatchNorm):
    """BatchNorm whose batch statistics are averaged across the ``dp`` mesh axis.

    Outside shard_map (single logical array) this is plain BatchNorm — the batch
    already spans the devices, XLA computes global-batch statistics when the input is
    dp-sharded, which is exactly the SyncBatchNorm semantic. ``axis_name`` matters
    when the layer runs inside an explicit ``shard_map`` region (per-device batch
    views): there the stats are pmean'd over the axis.
    """

    def __init__(self, in_channels: int = 0, num_devices: Optional[int] = None,
                 momentum: float = 0.9, epsilon: float = 1e-5,
                 axis_name: str = "dp", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
        self._axis_name = axis_name

    def forward(self, x):
        self._finish(x.shape[self._axis])
        gamma, beta = self.gamma.data(), self.beta.data()
        rmean, rvar = self.running_mean.data(), self.running_var.data()
        if not (autograd.is_training() and not self._use_global_stats):
            return nd.BatchNorm(x, gamma, beta, rmean, rvar, eps=self._eps,
                                fix_gamma=not self._scale, use_global_stats=True,
                                axis=self._axis)
        raw = x.data
        shape = [1] * raw.ndim
        shape[self._axis] = raw.shape[self._axis]

        def stats(raw_in):
            axes_ = tuple(i for i in range(raw_in.ndim) if i != self._axis)
            mu = jnp.mean(raw_in, axis=axes_)
            ms = jnp.mean(jnp.square(raw_in), axis=axes_)
            try:  # inside shard_map: average stats over the dp ring
                mu = lax.pmean(mu, self._axis_name)
                ms = lax.pmean(ms, self._axis_name)
            except NameError:
                pass  # no named axis: stats already span the global (sharded) batch
            return mu, ms - jnp.square(mu)

        def pure_fn(raw_in, g_in, b_in):
            mu, va = stats(raw_in)
            gg = g_in if self._scale else jnp.ones_like(g_in)
            o = (raw_in - mu.reshape(shape)) * lax.rsqrt(
                va.reshape(shape) + self._eps)
            return o * gg.reshape(shape) + b_in.reshape(shape)

        out = pure_fn(raw, gamma.data, beta.data)
        mean, var = stats(raw)
        m = self._momentum
        rmean._set_data(m * rmean.data + (1 - m) * mean)
        rvar._set_data(m * rvar.data + (1 - m) * var)
        result = NDArray(out)
        if autograd.is_recording():
            autograd.record_custom_node(pure_fn, [x, gamma, beta], [result])
        return result


class MultiHeadAttention(HybridBlock):
    """Flash-attention-backed MHA block (q,k,v projections + output projection).

    Input (B, T, C); ``num_heads`` must divide ``units``. For sequence-parallel long
    context, apply ``parallel.ring_self_attention`` to the projected q/k/v directly
    (this block's attention core is single-program flash attention).
    """

    def __init__(self, units: int, num_heads: int, use_bias: bool = True,
                 causal: bool = False, dropout: float = 0.0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        assert units % num_heads == 0
        self._units = units
        self._heads = num_heads
        self._causal = causal
        self._dropout = dropout
        with self.name_scope():
            self.q_proj = Dense(units, use_bias=use_bias, flatten=False)
            self.k_proj = Dense(units, use_bias=use_bias, flatten=False)
            self.v_proj = Dense(units, use_bias=use_bias, flatten=False)
            self.out_proj = Dense(units, use_bias=use_bias, flatten=False)

    def forward(self, x, memory=None):
        mem = x if memory is None else memory
        B, T, C = x.shape
        H = self._heads
        D = self._units // H
        q = self.q_proj(x).reshape((B, T, H, D)).transpose((0, 2, 1, 3))
        k = self.k_proj(mem).reshape((B, mem.shape[1], H, D)).transpose((0, 2, 1, 3))
        v = self.v_proj(mem).reshape((B, mem.shape[1], H, D)).transpose((0, 2, 1, 3))
        # Ulysses spec flip (active only under parallel.fsdp.layout_scope):
        # incoming activations are sequence-sharded; constraining q/k/v to the
        # head-sharded layout makes GSPMD emit the seq->head all-to-all, the
        # kernel sees the FULL sequence for its head group, and the output
        # constraint flips back (DeepSpeed-Ulysses as two reshards).
        q, k, v = (_layout_constrain(t, "head_activations") for t in (q, k, v))
        out = nd.contrib.flash_attention(q, k, v, causal=self._causal)
        out = out.transpose((0, 2, 1, 3)).reshape((B, T, self._units))
        out = _layout_constrain(out, "seq_activations")
        if self._dropout:
            out = nd.Dropout(out, p=self._dropout)
        return self.out_proj(out)
