"""gluon.contrib — parity with python/mxnet/gluon/contrib (SyncBatchNorm,
VariationalDropoutCell, attention blocks)."""

from . import nn
from .nn import SyncBatchNorm
from .rnn import VariationalDropoutCell
