"""tpulint core — AST linter for mxtpu's implicit runtime contracts.

The reference framework stays correct because every mutation is DECLARED to
its dependency engine (``docs/architecture/note_engine.md``); this port's
equivalents — ``donate_argnums`` ownership transfer, producer-thread batch
handoff, jit purity — are implicit conventions that nothing enforced.  PR 2's
donated-buffer/async-snapshot race and PR 4's multi-axis mis-reduction were
both found by hand.  ``tpulint`` machine-checks the convention layer: each
rule in ``mxtpu/analysis/rules/`` is grounded in one of those real bugs.

Usage (also via ``python -m mxtpu.analysis``)::

    from mxtpu.analysis import lint_paths
    findings = lint_paths(["mxtpu/"])

Per-line suppression: append ``# mxtpu: ignore[R001]`` (or a comma list, or
bare ``# mxtpu: ignore`` for all rules) to the flagged statement.  The
comment covers every physical line of the *logical* statement it sits in
(backslash and paren continuations included), so a suppression on any line
of a multi-line call silences findings anchored on its other lines; it never
leaks past the statement, so suppressions stay local and auditable.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "ModuleContext", "lint_source", "lint_file",
           "lint_paths", "dotted_name"]

_SUPPRESS_RE = re.compile(
    r"#\s*mxtpu:\s*ignore(?:\[([A-Za-z0-9_,\s]*)\])?")

# calls that enter a jax trace: a function passed to (or decorated by) one of
# these runs with tracer values, so host syncs / untracked randomness inside
# it are per-step hazards, not one-off host work
_TRACE_ENTRY_NAMES = {"jit", "pjit", "grad", "value_and_grad", "vjp",
                      "linearize", "vmap", "pmap", "shard_map"}


class Finding:
    """One lint hit: ``path:line:col RULE message``."""

    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path: str, line: int, col: int, rule: str,
                 message: str):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def __repr__(self):  # test-failure readability
        return f"Finding({self.format()!r})"

    def _key(self):
        return (self.path, self.line, self.col, self.rule, self.message)

    def __eq__(self, other):
        return isinstance(other, Finding) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())


def dotted_name(node) -> Optional[str]:
    """``ast`` expression → dotted name string (``jax.random.normal``), or
    None for anything that is not a plain Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_trace_entry(func) -> bool:
    name = dotted_name(func)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _TRACE_ENTRY_NAMES


class ModuleContext:
    """One parsed module plus the shared indexes the rules key off."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.lines = src.splitlines()
        self._parents: Optional[Dict[int, ast.AST]] = None
        self._suppress: Optional[Dict[int, Optional[Set[str]]]] = None
        self._functions_by_name: Optional[Dict[str, List[ast.AST]]] = None
        self._step_functions: Optional[List[ast.AST]] = None
        self._callgraph = None

    # -- tree plumbing ------------------------------------------------------
    def parent(self, node) -> Optional[ast.AST]:
        if self._parents is None:
            self._parents = {}
            for p in ast.walk(self.tree):
                for c in ast.iter_child_nodes(p):
                    self._parents[id(c)] = p
        return self._parents.get(id(node))

    def ancestors(self, node) -> Iterable[ast.AST]:
        p = self.parent(node)
        while p is not None:
            yield p
            p = self.parent(p)

    # -- suppression --------------------------------------------------------
    def _logical_groups(self):
        """Tokenize the source into logical statements: a list of
        ``(physical_line_span, comments)`` where ``comments`` is
        ``[(line, text), ...]``.  None if tokenization fails (the caller
        falls back to exact-physical-line suppression)."""
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.src).readline)
            groups = []
            cur_lines: Set[int] = set()
            cur_comments: List[Tuple[int, str]] = []
            has_code = False
            for tok in toks:
                tt = tok.type
                if tt == tokenize.COMMENT:
                    if has_code:             # trailing comment of a statement
                        cur_comments.append((tok.start[0], tok.string))
                        cur_lines.add(tok.start[0])
                    else:                    # standalone comment line
                        groups.append(({tok.start[0]},
                                       [(tok.start[0], tok.string)]))
                elif tt == tokenize.NEWLINE:  # logical line ends
                    cur_lines.update(range(tok.start[0], tok.end[0] + 1))
                    if has_code:
                        groups.append((cur_lines, cur_comments))
                    cur_lines, cur_comments, has_code = set(), [], False
                elif tt in (tokenize.NL, tokenize.INDENT, tokenize.DEDENT,
                            tokenize.ENDMARKER):
                    continue
                else:
                    has_code = True
                    cur_lines.update(range(tok.start[0], tok.end[0] + 1))
            if has_code:
                groups.append((cur_lines, cur_comments))
            return groups
        except (tokenize.TokenError, IndentationError, SyntaxError,
                ValueError):
            return None

    @staticmethod
    def _parse_suppress(text: str) -> Optional[object]:
        """``# mxtpu: ignore[...]`` comment text → None (all rules) or the
        rule-id set; ``False`` if the comment is not a suppression."""
        m = _SUPPRESS_RE.search(text)
        if not m:
            return False
        if m.group(1) is None:
            return None                      # bare ignore: every rule
        return {r.strip().upper() for r in m.group(1).split(",") if r.strip()}

    def _suppress_table(self) -> Dict[int, Optional[Set[str]]]:
        """line -> suppressed rule set (None = all).  A suppression comment
        covers every physical line of the logical statement carrying it."""
        table: Dict[int, Optional[Set[str]]] = {}

        def apply(lines: Iterable[int], rules):
            for ln in lines:
                if ln in table and (table[ln] is None or rules is None):
                    table[ln] = None
                elif ln in table:
                    table[ln] = table[ln] | rules
                else:
                    table[ln] = set(rules) if rules is not None else None

        groups = self._logical_groups()
        if groups is None:                   # unparseable: physical lines only
            for i, text in enumerate(self.lines, start=1):
                rules = self._parse_suppress(text)
                if rules is not False:
                    apply([i], rules)
            return table
        for span, comments in groups:
            for _cline, ctext in comments:
                rules = self._parse_suppress(ctext)
                if rules is False:
                    continue
                lo, hi = min(span), max(span)
                apply(range(lo, hi + 1), rules)
        return table

    def suppressed(self, line: int, rule: str) -> bool:
        if self._suppress is None:
            self._suppress = self._suppress_table()
        if line not in self._suppress:
            return False
        rules = self._suppress[line]
        return rules is None or rule.upper() in rules

    # -- function indexes ---------------------------------------------------
    def enclosing_scope(self, node) -> ast.AST:
        """Nearest enclosing function scope (ClassDef bodies are not name
        scopes for resolution purposes), else the module."""
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return a
        return self.tree

    def _scope_binds_name(self, scope, name: str) -> bool:
        """Does ``scope`` bind ``name`` other than by a ``def`` — as a
        parameter or a local store (assign/loop/with/import/except)?  Such a
        binding shadows any same-named outer function for everything nested
        inside ``scope`` (``while_loop(cond, ...)`` must not resolve its
        ``cond`` parameter to a module-level ``def cond``)."""
        from .dataflow import CFG, bindings_of
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            if any(d.name == name for d in CFG._param_defs(scope)):
                return True
        body = getattr(scope, "body", [])
        stack = list(body) if isinstance(body, list) else []
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue                     # nested scope: its own bindings
            if any(d.name == name and d.kind != "def"
                   for d in bindings_of(st)):
                return True
            for c in ast.iter_child_nodes(st):
                if isinstance(c, ast.stmt) or isinstance(c, ast.ExceptHandler):
                    stack.append(c)
        return False

    def resolve_function(self, name: str, at_node) -> List[ast.AST]:
        """Lexically resolve ``name`` at a reference site to function defs:
        innermost visible scope wins (a nested traced ``def step`` must not
        drag a same-named eager method into the traced set), and a parameter
        or local store of an inner scope shadows outer defs. Unresolvable
        names (parameters, imports) resolve to nothing rather than to every
        same-named def in the file."""
        cands = self.functions_by_name.get(name, [])
        if not cands:
            return []
        chain = [self.enclosing_scope(at_node)]
        while chain[-1] is not self.tree:
            chain.append(self.enclosing_scope(chain[-1]))
        for scope in chain:
            visible = [f for f in cands
                       if f is not scope and self.enclosing_scope(f) is scope]
            if visible:
                return visible
            if scope is not self.tree and self._scope_binds_name(scope, name):
                return []                    # shadowed before any def is seen
        return []

    @property
    def functions_by_name(self) -> Dict[str, List[ast.AST]]:
        if self._functions_by_name is None:
            idx: Dict[str, List[ast.AST]] = {}
            for n in ast.walk(self.tree):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    idx.setdefault(n.name, []).append(n)
            self._functions_by_name = idx
        return self._functions_by_name

    @property
    def callgraph(self):
        """The module's :class:`~mxtpu.analysis.callgraph.CallGraph` —
        call edges, traced-context propagation, loop-called closure."""
        if self._callgraph is None:
            from .callgraph import CallGraph
            self._callgraph = CallGraph(self)
        return self._callgraph

    @property
    def step_functions(self) -> List[ast.AST]:
        """Functions that flow into a jax trace (jit/grad/vmap/… entry):

        * decorated with ``@jax.jit`` / ``@partial(jax.jit, …)``;
        * passed as the first argument of a trace-entry call
          (``jax.jit(pure, donate_argnums=…)``, ``jax.value_and_grad(f)``),
          including ``self.method`` references and locally aliased names;
        * a function-valued argument of a jax control-flow HOF
          (``lax.scan`` / ``while_loop`` / ``cond`` / …);
        * defined inside, or reachable through the call graph from, one of
          the above — ``Name`` calls, ``self.m()`` method calls, and
          reaching-definition-resolved aliases (``h = helper; h(x)``).

        v2: computed by :class:`~mxtpu.analysis.callgraph.CallGraph`;
        resolution stays lexically scoped (innermost visible scope wins), so
        a traced inner ``def step`` does not drag a same-named eager method
        into the traced set.
        """
        if self._step_functions is None:
            self._step_functions = list(self.callgraph.traced_functions)
        return self._step_functions

    def in_step_function(self, node) -> bool:
        ids = {id(f) for f in self.step_functions}
        return any(id(a) in ids for a in self.ancestors(node)) \
            or id(node) in ids

    # -- threading/lock helpers (R004) --------------------------------------
    def lock_names(self) -> Set[str]:
        """Module-level names bound to threading.Lock/RLock and friends."""
        names: Set[str] = set()
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                callee = dotted_name(stmt.value.func) or ""
                if callee.rsplit(".", 1)[-1] in ("Lock", "RLock", "Semaphore",
                                                 "BoundedSemaphore",
                                                 "Condition"):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
        return names

    def spawns_threads(self) -> bool:
        """Evidence this module runs code on more than one thread: it
        constructs Thread/Lock/Event/… from ``threading``."""
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call):
                callee = dotted_name(n.func) or ""
                if callee.rsplit(".", 1)[-1] in (
                        "Thread", "Timer", "Lock", "RLock", "Semaphore",
                        "BoundedSemaphore", "Event", "Condition", "Barrier") \
                        and ("threading" in callee or "." not in callee):
                    return True
        return False

    def module_mutables(self) -> Set[str]:
        """Module-level names bound to a mutable container literal/ctor."""
        out: Set[str] = set()
        ctors = {"dict", "list", "set", "defaultdict", "Counter", "deque",
                 "OrderedDict", "WeakValueDictionary", "WeakSet"}
        for stmt in self.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            v = stmt.value
            mutable = isinstance(v, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp, ast.SetComp))
            if isinstance(v, ast.Call):
                callee = dotted_name(v.func) or ""
                mutable = callee.rsplit(".", 1)[-1] in ctors
            if mutable:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


def base_name(node) -> Optional[str]:
    """Peel Subscript/Attribute chains down to the root Name
    (``_state["events"].append`` → ``_state``)."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------


def _rules(select: Optional[Sequence[str]] = None,
           ignore: Optional[Sequence[str]] = None):
    from . import rules as rules_pkg
    active = []
    for mod in rules_pkg.RULES:
        rid = mod.RULE_ID
        if select and rid not in {s.upper() for s in select}:
            continue
        if ignore and rid in {s.upper() for s in ignore}:
            continue
        active.append(mod)
    return active


def lint_source(src: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None,
                ignore: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one module's source; returns unsuppressed findings sorted by
    position. A syntax error becomes a single E000 finding (the linter never
    crashes on an unparseable input file)."""
    try:
        ctx = ModuleContext(path, src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "E000",
                        f"syntax error: {e.msg}")]
    findings: Set[Finding] = set()
    for rule in _rules(select, ignore):
        for f in rule.check(ctx):
            if not ctx.suppressed(f.line, f.rule):
                findings.add(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


def lint_file(path: str, **kw) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return lint_source(f.read(), path=path, **kw)


def lint_paths(paths: Sequence[str], **kw) -> List[Finding]:
    """Lint files and/or directory trees (``.py`` files, skipping
    ``__pycache__``); paths are reported as given."""
    findings: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d != "__pycache__")
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        findings.extend(
                            lint_file(os.path.join(root, fname), **kw))
        else:
            findings.extend(lint_file(p, **kw))
    return findings
