"""mxtpu.analysis — tpulint static checker + runtime sanitizer suite.

The survey's core lesson from the reference is that a dependency-scheduling
engine stays correct because every mutation is DECLARED to it
(``docs/architecture/note_engine.md``).  This port replaced that engine with
implicit contracts — ``donate_argnums`` buffer ownership
(``step_cache.py``), producer-thread batch handoff (``device_feed.py``),
rank-0-only checkpoint commit (``checkpoint/manager.py``) — and both of the
hardest bugs so far (PR 2's donated-buffer/async-snapshot race, PR 4's
multi-axis mis-reduction) were found by hand.  This package machine-enforces
the contract layer, in the spirit of compiler sanitizers (ASan/TSan) and
JAX's ``transfer_guard``, specialized to this codebase:

* **Static half** (``lint.py`` + ``rules/``): an AST linter, runnable as
  ``python -m mxtpu.analysis <path>``, with logical-statement suppression
  (``# mxtpu: ignore[R001]``).  Rules R001–R010 cover host-sync-in-step,
  donation-use-after-pass, untracked nondeterminism, thread-shared mutables
  without a lock, overbroad excepts, span leaks, quant-cache materialize,
  unbounded maps, per-token host syncs, and blocking decode loops.  v2
  grounds the rules in a dataflow core — a statement-level CFG with
  reaching definitions (``dataflow.py``) and a module call graph with
  traced-context propagation (``callgraph.py``) — so cross-function forms
  (aliased helpers, ``self.m()`` methods, lax-HOF bodies) are caught, and
  ``--format json`` / ``--baseline`` support editor and ratchet workflows.
* **Program auditor** (``audit.py``, ``python -m mxtpu.analysis --audit``):
  abstractly traces the canonical compiled programs (fused step, serving
  decode/verify/prefill, sharded fsdp×tp decode, ZeRO update) on a virtual
  mesh and verifies jaxpr/HLO-level invariants — shardcheck (A101–A104),
  collective/transfer budgets (A201/A202), retrace-key closure (A301);
  ``--audit --expect-fail`` seeds each violation class to prove detection.
* **Runtime half** (``sanitize.py``): opt-in via
  ``MXTPU_SANITIZE=transfers,donation,retrace,threads`` — transfer guards
  around the fused step, donated-buffer poisoning, retrace escalation with
  a signature diff, and thread-ownership assertions.  Counters land in
  ``profiler.get_sanitizer_stats()``.

See ``docs/static_analysis.md`` for the rule catalog and knob map.
"""

from .lint import Finding, lint_file, lint_paths, lint_source
from . import sanitize
from .sanitize import (DonationError, HostSyncError, RetraceError,
                       SanitizerError, ThreadOwnershipError)

__all__ = ["Finding", "lint_file", "lint_paths", "lint_source", "sanitize",
           "SanitizerError", "HostSyncError", "DonationError", "RetraceError",
           "ThreadOwnershipError"]
