"""Runtime sanitizers — the ASan/TSan-style twin of the tpulint rules.

Opt-in via ``MXTPU_SANITIZE=transfers,donation,retrace,threads`` (or
``all``), or programmatically via :func:`configure` / :func:`scope`.  Each
mode arms one hazard detector at the exact choke points the static rules
reason about, and every check/trip lands in
``profiler.get_sanitizer_stats()``:

* ``transfers`` — wraps the fused step's compiled-program execution in
  ``jax.transfer_guard("disallow")`` so an implicit host transfer per step
  fails loudly (R001's runtime twin), and re-names trace-time
  concretization errors (``.asnumpy()`` on a tracer) as
  :class:`HostSyncError`.
* ``donation`` — poisons the buffer references a ``donate_argnums`` step
  consumed; a later read through an ``NDArray`` handle raises
  :class:`DonationError` naming the donating step, instead of XLA's opaque
  "Array has been deleted" (and instead of silently working on CPU, where
  XLA skips donation — the PR 2 snapshot race was invisible on CPU for
  exactly that reason).
* ``retrace`` — escalates a compile-cache signature miss beyond
  ``MXTPU_SANITIZE_RETRACE_LIMIT`` (default 2: train + eval) into a
  :class:`RetraceError` carrying a structural signature diff — which
  shape/dtype/sharding/hyperparameter changed.
* ``threads`` — asserts ownership transitions: a DeviceFeed batch delivered
  to the consumer is never re-enqueued, checkpoint snapshots are
  host-landed before the next (donating) step can run, and checkpoint
  writes happen on the owning writer thread.
"""

from __future__ import annotations

import os
import threading
import weakref
from contextlib import contextmanager
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["SanitizerError", "HostSyncError", "DonationError", "RetraceError",
           "ThreadOwnershipError", "configure", "active", "enabled", "scope",
           "MODES", "poison", "clear_poison", "step_guard",
           "escalate_retrace", "sig_diff", "assert_fresh_delivery",
           "assert_host_landed", "assert_owner_thread"]

MODES = ("transfers", "donation", "retrace", "threads")

_EMPTY = frozenset()
_active: Optional[frozenset] = None
_retrace_limit = 2
_lock = threading.Lock()


# ---------------------------------------------------------------------------
# named errors (each carries the lint rule it is the runtime twin of)
# ---------------------------------------------------------------------------


class SanitizerError(RuntimeError):
    """Base of all sanitizer trips; ``mode`` and ``rule`` name the detector."""

    mode = "sanitize"
    rule = "R000"

    def __init__(self, msg: str):
        super().__init__(f"mxtpu sanitizer [{self.mode}/{self.rule}]: {msg}")


class HostSyncError(SanitizerError):
    mode = "transfers"
    rule = "R001"


class DonationError(SanitizerError):
    mode = "donation"
    rule = "R002"


class RetraceError(SanitizerError):
    mode = "retrace"
    rule = "retrace"


class ThreadOwnershipError(SanitizerError):
    mode = "threads"
    rule = "R004"


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


def configure(spec: Optional[str] = None,
              retrace_limit: Optional[int] = None) -> frozenset:
    """(Re)parse the sanitizer configuration.

    ``spec`` overrides ``MXTPU_SANITIZE`` (comma list of modes, or ``all``);
    ``retrace_limit`` overrides ``MXTPU_SANITIZE_RETRACE_LIMIT`` (max
    distinct signatures one step cache may compile before escalation).
    Unknown modes raise ValueError — a typo must not silently disarm a
    sanitizer run.
    """
    global _active, _retrace_limit
    raw = os.environ.get("MXTPU_SANITIZE", "") if spec is None else spec
    modes = set()
    for tok in str(raw).replace(";", ",").split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        if tok in ("all", "1", "on", "true"):
            modes.update(MODES)
        elif tok in MODES:
            modes.add(tok)
        else:
            raise ValueError(
                f"MXTPU_SANITIZE: unknown mode {tok!r} (choose from "
                f"{', '.join(MODES)} or 'all')")
    with _lock:
        _active = frozenset(modes)
        if retrace_limit is not None:
            _retrace_limit = max(1, int(retrace_limit))
        else:
            try:
                _retrace_limit = max(1, int(os.environ.get(
                    "MXTPU_SANITIZE_RETRACE_LIMIT", "2")))
            except ValueError:
                _retrace_limit = 2
    _install_hooks()
    return _active


def active() -> frozenset:
    """The armed mode set (lazily parsed from ``MXTPU_SANITIZE`` on first
    use; cheap enough for per-step calls)."""
    if _active is None:
        return configure()
    return _active


def enabled(mode: str) -> bool:
    return mode in active()


def retrace_limit() -> int:
    if _active is None:
        configure()
    return _retrace_limit


@contextmanager
def scope(spec: str, retrace_limit: Optional[int] = None):
    """Temporarily arm a mode set (tests, ``bench.py --sanitize`` legs);
    restores the previous configuration and clears poisons on exit."""
    prev_active, prev_limit = _active, _retrace_limit
    configure(spec, retrace_limit=retrace_limit)
    try:
        yield active()
    finally:
        clear_poison()
        with _lock:
            globals()["_active"] = prev_active
            globals()["_retrace_limit"] = prev_limit
        _install_hooks()


def _install_hooks():
    """Arm/disarm the NDArray read hook (donation poisons)."""
    try:
        from ..ndarray import ndarray as nd_mod
    except ImportError:     # package still importing: step() installs later
        return
    on = _active is not None and "donation" in _active
    nd_mod._sanitize_data_hook = _check_poison if on else None


def _record(key: str, n: int = 1):
    from .. import profiler
    profiler.record_sanitizer(key, n)


# ---------------------------------------------------------------------------
# donation poisoning (R002 runtime twin)
# ---------------------------------------------------------------------------

# id(array) -> (weakref, origin). A weakref (not the array) so poisoning
# never extends buffer lifetime; the finalizer retires the entry, and the
# identity re-check on read makes id reuse harmless.
_poisoned: Dict[int, Tuple[weakref.ref, str]] = {}


def poison(arrays: Iterable, origin: str):
    """Mark buffers a donating program consumed: any later read through an
    NDArray handle raises :class:`DonationError`.  On CPU (where XLA skips
    donation and the stale read would silently 'work') this makes the
    accelerator ownership contract enforceable in CI."""
    armed = 0
    for a in arrays:
        if a is None or not hasattr(a, "dtype"):
            continue
        key = id(a)
        try:
            r = weakref.ref(a, lambda _ref, _key=key: _poisoned.pop(_key, None))
        except TypeError:
            continue
        _poisoned[key] = (r, origin)
        armed += 1
    if armed:
        _record("donation_poisons_armed", armed)


def clear_poison():
    _poisoned.clear()


def _check_poison(raw):
    """NDArray read hook (installed as ``ndarray._sanitize_data_hook``)."""
    ent = _poisoned.get(id(raw))
    if ent is not None and ent[0]() is raw:
        _record("donation_trips")
        raise DonationError(
            f"read of a buffer that was donated to {ent[1]} — on "
            f"accelerators this array is already deleted (XLA would raise "
            f"an opaque 'Array has been deleted'); copy the value before "
            f"the donating step, or read the step's returned arrays")


# ---------------------------------------------------------------------------
# transfer guard (R001 runtime twin)
# ---------------------------------------------------------------------------


def _is_transfer_error(e: BaseException) -> bool:
    s = str(e)
    return "isallowed" in s and "transfer" in s


@contextmanager
def step_guard(san: frozenset, traced_now: bool, where: str = "fused step"):
    """Guard one compiled-step execution.

    On a cache-hit execution, ``jax.transfer_guard("disallow")`` turns any
    implicit host transfer into :class:`HostSyncError`.  On the trace call
    the guard stays off (tracing legitimately ships constants to the
    device); instead, trace-time concretizations (``.asnumpy()`` / ``float``
    on a tracer — the lint rule R001 shapes) are re-raised as
    :class:`HostSyncError` so CI names the bug instead of printing a
    300-line tracer error.
    """
    if "transfers" not in san:
        yield
        return
    import jax
    if traced_now:
        try:
            yield
        except Exception as e:
            if e.__class__.__name__ in ("TracerArrayConversionError",
                                        "ConcretizationTypeError",
                                        "TracerBoolConversionError"):
                _record("transfer_trips")
                raise HostSyncError(
                    f"host sync inside the traced {where}: {e}") from e
            raise
    else:
        _record("transfer_guards")
        try:
            with jax.transfer_guard("disallow"):
                yield
        except Exception as e:
            if _is_transfer_error(e):
                _record("transfer_trips")
                raise HostSyncError(
                    f"implicit host transfer while executing the compiled "
                    f"{where}: {e}") from e
            raise


# ---------------------------------------------------------------------------
# retrace escalation (+ signature diffing)
# ---------------------------------------------------------------------------


def sig_diff(old, new, labels: Optional[Sequence[str]] = None,
             max_entries: int = 8) -> str:
    """Structural diff of two cache signatures → "which key changed".

    Tuples/lists are descended elementwise (``labels`` names the top-level
    components); a 3-tuple ``(shape, dtype, sharding)`` — the framework's
    array signature — gets field names.  Output like
    ``params[0].dtype: 'float32' -> 'float16'``.
    """
    out = []

    def walk(path, a, b):
        if len(out) >= max_entries:
            return
        if type(a) is type(b) and isinstance(a, (tuple, list)):
            if len(a) != len(b):
                out.append(f"{path or 'sig'}: arity {len(a)} -> {len(b)}")
                return
            arr_sig = (len(a) == 3 and isinstance(a[0], tuple)
                       and isinstance(a[1], str))
            for i, (x, y) in enumerate(zip(a, b)):
                if arr_sig:
                    field = ("shape", "dtype", "sharding")[i]
                    walk(f"{path}.{field}" if path else field, x, y)
                elif labels is not None and not path and i < len(labels):
                    walk(labels[i], x, y)
                else:
                    walk(f"{path}[{i}]" if path else f"[{i}]", x, y)
        elif a != b:
            out.append(f"{path or 'sig'}: {a!r} -> {b!r}")

    walk("", old, new)
    return "; ".join(out) if out else "signatures differ structurally"


def escalate_retrace(cache_name: str, n_cached: int, old_sig, new_sig,
                     labels: Optional[Sequence[str]] = None):
    """Raise when a step cache is about to compile one signature too many.

    ``n_cached`` is how many signatures the cache already holds; the limit
    (default 2 — a train + eval pair, the compile-guard contract) comes from
    :func:`configure`.  The error carries the structural diff against the
    most recently used signature: the changed shape/dtype/sharding/
    hyperparameter is named instead of leaving the reader to eyeball two
    500-element tuples.
    """
    if n_cached < retrace_limit():
        return
    _record("retrace_escalations")
    diff = sig_diff(old_sig, new_sig, labels=labels)
    raise RetraceError(
        f"cache '{cache_name}' would compile signature #{n_cached + 1} "
        f"(limit {retrace_limit()}; raise MXTPU_SANITIZE_RETRACE_LIMIT if "
        f"this loop legitimately multi-compiles) — changed vs last step: "
        f"{diff}")


# ---------------------------------------------------------------------------
# thread-ownership assertions (R004 runtime twin)
# ---------------------------------------------------------------------------

# id -> batch, weak so consumed batches don't accumulate; the identity
# re-check makes id reuse after GC harmless
_delivered: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


def assert_fresh_delivery(batch, origin: str = "DeviceFeed"):
    """Producer-side: a batch handed to the consumer must never be enqueued
    again — the consumer may donate its buffers the moment it takes it."""
    _record("ownership_checks")
    prev = _delivered.get(id(batch))
    if prev is batch:
        _record("ownership_trips")
        raise ThreadOwnershipError(
            f"{origin}: batch re-enqueued after delivery — the consumer owns "
            f"it (and may have donated its buffers to a fused step)")
    try:
        _delivered[id(batch)] = batch
    except TypeError:
        pass            # not weakref-able: can't track, don't crash


def assert_host_landed(arrays: Dict[str, object], origin: str):
    """Checkpoint-side: every snapshot array must be host-resident before
    ``save()`` returns — the next step's donation deletes device buffers a
    reference-only snapshot would still point at (the PR 2 race)."""
    _record("ownership_checks")
    bad = [k for k, v in arrays.items() if not isinstance(v, np.ndarray)]
    if bad:
        _record("ownership_trips")
        raise ThreadOwnershipError(
            f"{origin}: snapshot entries {bad[:5]} are not host-landed "
            f"numpy arrays — a donating step can delete the device buffers "
            f"they reference before the writer serializes them")


def assert_owner_thread(owner: Optional[threading.Thread], origin: str):
    """Assert the current thread is the declared owner of a transition
    (e.g. checkpoint serialization happens on the writer thread only)."""
    _record("ownership_checks")
    if owner is not None and threading.current_thread() is not owner:
        _record("ownership_trips")
        raise ThreadOwnershipError(
            f"{origin}: ran on thread {threading.current_thread().name!r} "
            f"but is owned by {owner.name!r}")
