"""R010 blocking-call-in-decode-loop: network/transport I/O inside a
scheduler decode loop.

The multi-replica serving contract (``mxtpu.serving.router``) is that
routing reads are LOCK-FREE SNAPSHOTS: a router polls ``engine.load()``
(or scrapes a remote exporter) from its own thread, and the engine's
scheduler loop never waits on anything slower than its own dispatch. The
tempting inversion — the scheduler loop itself phoning a peer, scraping a
metrics endpoint, or rendezvousing over the ``mxtpu.dist`` transport once
per decode turn — couples every slot's inter-token latency to network
tail latency: one 200 ms scrape stall is a 200 ms token stall for the
whole batch, and on the tunneled TPU runtime the decode program sits idle
while the socket blocks. The failure is invisible to bit-exactness tests;
only p99 inter-token latency shows it.

Flagged: a blocking network/transport call — ``urlopen``/``requests.*``
fetches, ``socket`` connects, ``recv``/``sendall``/``getresponse``, or a
connect/barrier/scrape-family method on a transport-named receiver
(``transport``/``sock``/``conn``/``http``/``channel``/``session``) —
**inside a ``for``/``while`` loop** of a scheduler-family function (name
containing ``sched``/``decode``/``serve``/``dispatch``/``turn``). The
blessed shapes never trip: the router's own polling loops live outside the
engine (no scheduler-family enclosing function), drain/adopt transport
use sits outside the decode loop, and an exporter scrape runs on its own
daemon thread.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R010"
TITLE = "blocking-call-in-decode-loop"

# unambiguous blocking network calls, by dotted name
_NET_FUNCS = {"urllib.request.urlopen", "urlopen", "requests.get",
              "requests.post", "requests.put", "requests.request",
              "socket.create_connection", "http.client.HTTPConnection"}
# unambiguous blocking socket/HTTP methods, any receiver
_NET_METHODS = {"recv", "recv_into", "recvfrom", "sendall", "getresponse",
                "urlopen"}
# connect/sync-family methods that block only when the receiver is a
# network/transport object — gated on the receiver's name
_TRANSPORT_METHODS = {"connect", "disconnect", "barrier", "scrape",
                      "fetch", "request", "get", "post", "send",
                      "rendezvous", "wait"}
_TRANSPORT_HINTS = ("transport", "socket", "sock", "conn", "http",
                    "channel", "session", "client", "peer")

# a scheduler-family function: the engine's decode/dispatch path, where a
# blocking call inside a loop stalls every slot's next token
_SCHED_HINTS = ("sched", "decode", "serve", "dispatch", "turn")


def _names_transport(node) -> bool:
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name is not None:
            low = name.lower()
            if any(h in low for h in _TRANSPORT_HINTS):
                return True
    return False


def _sched_loop(ctx, node) -> bool:
    """In a for/while loop AND under a scheduler-family function."""
    in_loop = in_sched_fn = False
    for a in ctx.ancestors(node):
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            in_loop = True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            low = a.name.lower()
            if any(h in low for h in _SCHED_HINTS):
                in_sched_fn = True
    return in_loop and in_sched_fn


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        hit = None
        name = dotted_name(node.func)
        if name is not None and name in _NET_FUNCS:
            hit = f"{name}()"
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _NET_METHODS:
                hit = f".{attr}()"
            elif attr in _TRANSPORT_METHODS \
                    and _names_transport(node.func.value):
                hit = f".{attr}()"
        if hit is None or not _sched_loop(ctx, node):
            continue
        yield Finding(
            ctx.path, node.lineno, node.col_offset, RULE_ID,
            f"{TITLE}: {hit} blocks the scheduler decode loop on network "
            f"I/O — every slot's next token now waits on tail latency. "
            f"Routing reads must be lock-free snapshots (engine.load()); "
            f"move the call to the router/exporter thread or outside the "
            f"per-turn loop")
