"""R004 thread-shared-mutable-without-lock: module state raced by threads.

This codebase runs real producer threads — the DeviceFeed feeder
(``device_feed.py``), the checkpoint writer (``checkpoint/manager.py``) —
that bump module-level stat dicts (``profiler``'s counters) concurrently
with the main thread.  CPython's GIL makes single bytecodes atomic but NOT
read-modify-write sequences (``d[k] += 1``, paired ``total``/``last``
updates), so unlocked counters silently drop updates or tear.  The rule
fires on mutation of a module-level dict/list/set inside any function of a
module that demonstrably spawns threads (constructs ``threading.Thread`` /
``Lock`` / ``Event`` …), unless the mutation happens under a ``with <lock>``
whose context name looks like (or is module-bound to) a lock.  The runtime
twin is ``MXTPU_SANITIZE=threads`` (ownership-transition assertions).
"""

from __future__ import annotations

import ast

from ..lint import Finding, base_name, dotted_name

RULE_ID = "R004"
TITLE = "thread-shared-mutable-without-lock"

_MUTATORS = {"update", "setdefault", "pop", "popitem", "clear", "append",
             "extend", "insert", "remove", "add", "discard", "appendleft",
             "sort", "reverse"}


def _under_lock(ctx, node, lock_names) -> bool:
    for a in ctx.ancestors(node):
        if isinstance(a, (ast.With, ast.AsyncWith)):
            for item in a.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call):
                    expr = expr.func      # with lock_factory(): …
                name = dotted_name(expr) or ""
                leaf = name.rsplit(".", 1)[-1].lower()
                if "lock" in leaf or "mutex" in leaf \
                        or name in lock_names:
                    return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break                         # don't credit an outer scope's with
    return False


def check(ctx):
    if not ctx.spawns_threads():
        return
    mutables = ctx.module_mutables()
    if not mutables:
        return
    lock_names = ctx.lock_names()
    seen = set()

    def flag(node, name, how):
        key = (node.lineno, node.col_offset)
        if key in seen:
            return None
        seen.add(key)
        return Finding(
            ctx.path, node.lineno, node.col_offset, RULE_ID,
            f"{TITLE}: module-level '{name}' {how} without holding a lock, "
            f"in a module that spawns threads — wrap the mutation in the "
            f"module's lock (producer threads race the main thread on it)")

    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            name, how, anchor = None, None, node
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        n = base_name(t)
                        if n in mutables:
                            name, how = n, "is written"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    n = base_name(t)
                    if isinstance(t, ast.Subscript) and n in mutables:
                        name, how = n, "has an entry deleted"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                n = base_name(node.func.value)
                if n in mutables:
                    name, how = n, f"is mutated via .{node.func.attr}()"
            if name and not _under_lock(ctx, anchor, lock_names):
                f = flag(anchor, name, how)
                if f:
                    yield f
