"""tpulint rule registry.

Each rule module exposes ``RULE_ID``, ``TITLE``, and
``check(ctx: ModuleContext) -> Iterable[Finding]``.  Rules are grounded in
this repo's real bug history (see ``docs/static_analysis.md`` for the
catalog and the PR 2 / PR 4 incidents each one would have caught).
"""

from . import (host_sync, donation, nondeterminism, thread_shared, excepts,
               span_leak, quant_dequant, unbounded_map, accept_sync,
               router_block)

RULES = [host_sync, donation, nondeterminism, thread_shared, excepts,
         span_leak, quant_dequant, unbounded_map, accept_sync,
         router_block]

__all__ = ["RULES"]
