"""R006 span-leak: ``tracer.span(...)`` opened and never closed.

``tracer.span`` returns a context manager; the duration event is only
recorded when the span EXITS. A bare call (``tracer.span("step/x")`` as a
statement) silently records nothing — worse, the reader assumes the region
is timed, so the gap in the trace gets misdiagnosed as idle time. The
telemetry-plane work made spans the backbone of request timelines and
flight-recorder bundles, which is exactly when a leaked span turns into a
missing forensic record.

Blessed patterns (not flagged):

* ``with tracer.span(...):`` — the normal form;
* returning/yielding the span (ownership handed to the caller);
* passing it straight into another call (``stack.enter_context(...)``);
* binding it to a name that the enclosing scope later uses as a context
  manager, calls ``__enter__``/``close``/``__exit__`` on, or passes on.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R006"
TITLE = "span-leak"

# qualifiers that make a ``.span(...)`` call the tracer's (vs some other
# object's unrelated ``span`` method)
_QUALS = ("tracer", "observability", "profiler")


def _is_span_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] != "span":
        return False
    if len(parts) == 1:
        return True          # bare span() — from-import of tracer.span
    return any(q in seg for seg in parts[:-1] for q in _QUALS)


def _name_is_closed(scope: ast.AST, var: str, after_line: int) -> bool:
    """Does ``scope`` ever treat ``var`` as a managed/closed span after the
    binding line? (with-statement, __enter__/__exit__/close, or passing the
    span onward — e.g. into ``ExitStack.enter_context``)."""
    for n in ast.walk(scope):
        if isinstance(n, ast.withitem):
            c = n.context_expr
            if isinstance(c, ast.Name) and c.id == var:
                return True
        elif isinstance(n, ast.Attribute) and n.attr in (
                "__enter__", "__exit__", "close"):
            v = n.value
            if isinstance(v, ast.Name) and v.id == var:
                return True
        elif isinstance(n, ast.Call):
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(arg, ast.Name) and arg.id == var \
                        and getattr(n, "lineno", 0) >= after_line:
                    return True
        elif isinstance(n, (ast.Return, ast.Yield)) and n.value is not None:
            if isinstance(n.value, ast.Name) and n.value.id == var:
                return True
    return False


def _blessed(ctx, call: ast.Call) -> bool:
    parent = ctx.parent(call)
    if isinstance(parent, ast.withitem):
        return True
    if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom, ast.Await)):
        return True
    if isinstance(parent, ast.Call):
        # the span value flows into another call (enter_context and kin)
        return True
    if isinstance(parent, ast.Attribute) and parent.attr in (
            "__enter__", "close"):
        return True   # tracer.span(...).__enter__() — explicit management
    if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
        targets = parent.targets if isinstance(parent, ast.Assign) \
            else [parent.target]
        scope = ctx.enclosing_scope(call)
        for t in targets:
            if isinstance(t, ast.Name) \
                    and _name_is_closed(scope, t.id, call.lineno):
                return True
        return False
    return False


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not _is_span_call(node):
            continue
        if _blessed(ctx, node):
            continue
        yield Finding(
            ctx.path, node.lineno, node.col_offset, RULE_ID,
            f"{TITLE}: tracer.span(...) opened without `with` (or explicit "
            f"close) — the duration event is recorded on exit, so this span "
            f"never lands in the trace; use `with tracer.span(...):` or "
            f"hand the span to an ExitStack")
