"""R007 quant-cache-materialize: ``QuantKV.dequantize()`` inside a traced
step.

``QuantKV.dequantize()`` materializes the FULL-precision view of a
quantized cache — a debugging/test convenience. Inside a jit-traced
serving/step function it silently rebuilds the (S, H, TOT, D) f32 cache
every decode step, which is exactly the regression ISSUE 16 removed: PR
14's serving read dequantized the whole per-layer cache before the score
einsum and ``quant_decode_speedup`` ratcheted at 0.78 (quantization paid
in bytes, charged in time). The fused read
(``mxtpu.ops.quant_attention.dequant_attention_decode``) consumes the
quantized storage directly; per-ROW reads (``dequantize_rows`` on one
gathered row, e.g. the embedding lookup) are fine and not flagged.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R007"
TITLE = "quant-cache-materialize"

# receivers whose .dequantize() is (or aliases) a QuantKV cache — the rule
# stays name-based like the rest of tpulint: any .dequantize() attribute
# call counts, because the method only exists on QuantKV in this codebase
_METHOD = "dequantize"


def check(ctx):
    seen = set()
    for fn in ctx.step_functions:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == _METHOD):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            seen.add(key)
            recv = dotted_name(node.func.value) or "<cache>"
            yield Finding(
                ctx.path, node.lineno, node.col_offset, RULE_ID,
                f"{TITLE}: {recv}.dequantize() inside a function that flows "
                f"into a jax trace materializes the full-precision KV view "
                f"every step (the 0.78x quant_decode_speedup regression) — "
                f"use mxtpu.ops.quant_attention.dequant_attention_decode to "
                f"read the quantized cache fused, or dequantize_rows on the "
                f"single gathered row")
