"""R009 per-token-host-sync: accept-count readback inside a scheduler loop.

The speculative-decode contract (``mxtpu.serving.spec``) is ONE sanctioned
host readback per verify dispatch: the engine lands ``(outs, lives)`` with
a single ``np.asarray`` pair and every per-slot decision — how many tokens
were accepted, what to emit, where the cursor moved — runs on that host
copy.  The tempting alternative is a per-slot (or worse, per-token) loop
that calls ``.item()`` / ``int()`` / ``np.asarray()`` on the DEVICE
accept-count array each iteration; on the tunneled TPU runtime each such
call is a 30–100 ms device→host round trip, so a k=4 verify over 8 slots
pays up to 32 syncs for a dispatch whose entire point was to cost one.
The win silently inverts: speculation *slows decode down* while every
bit-exactness test stays green.

Flagged: a host-materializing call (``.item()`` / ``.tolist()`` /
``int()`` / ``float()`` / ``np.asarray()``-family) **inside a ``for`` /
``while`` loop** whose receiver/argument names an accept/verify-family
value (``accept``/``accepted``/``accept_len``/``lives``/``verify_out``
substrings).  The blessed shape — the one readback outside the loop,
host-side indexing inside — never trips: names carrying a host-copy
suffix (``lives_np`` / ``accepts_host`` / ``*_cpu``) are exempt, as are
static quantities (``int(x.shape[0])``, ``len(...)``), mirroring R001.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R009"
TITLE = "per-token-host-sync"

# substrings marking an accept/verify-family value (the arrays the verify
# program returns and the per-slot accept accounting derives from)
_ACCEPT_HINTS = ("accept", "lives", "verify_out")

_SYNC_METHODS = {"item", "asscalar", "tolist", "asnumpy"}
_SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get", "device_get"}
_CONCRETIZERS = {"int", "float", "bool"}
# static (python-int) quantities: int(acc.shape[0]) is not a host sync
_STATIC_HINTS = {"shape", "ndim", "size", "len", "range", "dtype", "dims"}


# suffixes declaring "already landed on the host" — the blessed readback
# names its numpy copies this way (outs_np / lives_np), and touching those
# in a loop is exactly the pattern the rule steers toward
_HOST_SUFFIXES = ("_np", "_host", "_cpu")


def _mentions_accept(node) -> bool:
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name is not None:
            low = name.lower()
            if any(low.endswith(s) for s in _HOST_SUFFIXES):
                continue
            if any(h in low for h in _ACCEPT_HINTS):
                return True
    return False


def _mentions_static(node) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Call) and (dotted_name(n.func) or "") == "len":
            return True
    return False


def _in_loop(ctx, node) -> bool:
    return any(isinstance(a, (ast.For, ast.AsyncFor, ast.While))
               for a in ctx.ancestors(node))


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target = None
        hit = None
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SYNC_METHODS:
            target = node.func.value
            hit = f".{node.func.attr}()"
        else:
            name = dotted_name(node.func)
            if name in _SYNC_FUNCS and node.args:
                target = node.args[0]
                hit = f"{name}()"
            elif name in _CONCRETIZERS and len(node.args) == 1:
                target = node.args[0]
                hit = f"{name}()"
        if target is None or not _mentions_accept(target) \
                or _mentions_static(target) or not _in_loop(ctx, node):
            continue
        yield Finding(
            ctx.path, node.lineno, node.col_offset, RULE_ID,
            f"{TITLE}: {hit} on an accept/verify-family array inside a "
            f"loop syncs the host once per iteration — land (outs, lives) "
            f"with ONE np.asarray per verify dispatch outside the loop and "
            f"index the host copy inside it")
