"""R009 per-token-host-sync: accept-count readback inside a scheduler loop.

The speculative-decode contract (``mxtpu.serving.spec``) is ONE sanctioned
host readback per verify dispatch: the engine lands ``(outs, lives)`` with
a single ``np.asarray`` pair and every per-slot decision — how many tokens
were accepted, what to emit, where the cursor moved — runs on that host
copy.  The tempting alternative is a per-slot (or worse, per-token) loop
that calls ``.item()`` / ``int()`` / ``np.asarray()`` on the DEVICE
accept-count array each iteration; on the tunneled TPU runtime each such
call is a 30–100 ms device→host round trip, so a k=4 verify over 8 slots
pays up to 32 syncs for a dispatch whose entire point was to cost one.
The win silently inverts: speculation *slows decode down* while every
bit-exactness test stays green.

Flagged: a host-materializing call (``.item()`` / ``.tolist()`` /
``int()`` / ``float()`` / ``np.asarray()``-family) **inside a ``for`` /
``while`` loop** whose receiver/argument names an accept/verify-family
value (``accept``/``accepted``/``accept_len``/``lives``/``verify_out``
substrings).  The blessed shape — the one readback outside the loop,
host-side indexing inside — never trips: names carrying a host-copy
suffix (``lives_np`` / ``accepts_host`` / ``*_cpu``) are exempt, as are
static quantities (``int(x.shape[0])``, ``len(...)``), mirroring R001.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R009"
TITLE = "per-token-host-sync"

# substrings marking an accept/verify-family value (the arrays the verify
# program returns and the per-slot accept accounting derives from)
_ACCEPT_HINTS = ("accept", "lives", "verify_out")

_SYNC_METHODS = {"item", "asscalar", "tolist", "asnumpy"}
_SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "jax.device_get", "device_get"}
_CONCRETIZERS = {"int", "float", "bool"}
# static (python-int) quantities: int(acc.shape[0]) is not a host sync
_STATIC_HINTS = {"shape", "ndim", "size", "len", "range", "dtype", "dims"}


# suffixes declaring "already landed on the host" — the blessed readback
# names its numpy copies this way (outs_np / lives_np), and touching those
# in a loop is exactly the pattern the rule steers toward
_HOST_SUFFIXES = ("_np", "_host", "_cpu")


def _mentions_accept(node) -> bool:
    for n in ast.walk(node):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name is not None:
            low = name.lower()
            if any(low.endswith(s) for s in _HOST_SUFFIXES):
                continue
            if any(h in low for h in _ACCEPT_HINTS):
                return True
    return False


def _mentions_static(node) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Call) and (dotted_name(n.func) or "") == "len":
            return True
    return False


def _in_loop(ctx, node) -> bool:
    return any(isinstance(a, (ast.For, ast.AsyncFor, ast.While))
               for a in ctx.ancestors(node))


def _sync_target(node):
    """(synced expression, display form) of a host-materializing call."""
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SYNC_METHODS:
        return node.func.value, f".{node.func.attr}()"
    name = dotted_name(node.func)
    if name in _SYNC_FUNCS and node.args:
        return node.args[0], f"{name}()"
    if name in _CONCRETIZERS and len(node.args) == 1:
        return node.args[0], f"{name}()"
    return None, None


def _param_index(fn, name: str):
    a = fn.args
    params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    return params.index(name) if name in params else None


def _root_name(node):
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_hostcopy(node) -> bool:
    """The argument is a declared host copy (``lives_np`` etc.) — syncing it
    again is free, so the cross-function form must not fire."""
    root = _root_name(node)
    return root is not None and \
        any(root.lower().endswith(s) for s in _HOST_SUFFIXES)


def _loop_sites_of(ctx, fn):
    """Loop call sites targeting ``fn`` across the module's call graph."""
    out = []
    for pairs in ctx.callgraph.edges.values():
        for callee, site in pairs:
            if callee is fn and _in_loop(ctx, site):
                out.append(site)
    return out


def _accept_at_site(ctx, fn, idx, depth=0):
    """Does some loop call site of ``fn`` pass an accept-family value at
    positional ``idx``?  Follows one parameter hop per level (helper one or
    two frames below the loop), bounded."""
    if idx is None or depth > 3:
        return None
    for site in _loop_sites_of(ctx, fn):
        if idx >= len(site.args):
            continue
        arg = site.args[idx]
        if _mentions_accept(arg) and not _mentions_static(arg):
            return site
        root = _root_name(arg)
        if root is not None:
            caller = ctx.enclosing_scope(site)
            if isinstance(caller, (ast.FunctionDef, ast.AsyncFunctionDef)):
                up = _accept_at_site(ctx, caller, _param_index(caller, root),
                                     depth + 1)
                if up is not None:
                    return site
    return None


def check(ctx):
    flagged = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target, hit = _sync_target(node)
        if target is None or not _mentions_accept(target) \
                or _mentions_static(target) or not _in_loop(ctx, node):
            continue
        flagged.add((node.lineno, node.col_offset))
        yield Finding(
            ctx.path, node.lineno, node.col_offset, RULE_ID,
            f"{TITLE}: {hit} on an accept/verify-family array inside a "
            f"loop syncs the host once per iteration — land (outs, lives) "
            f"with ONE np.asarray per verify dispatch outside the loop and "
            f"index the host copy inside it")

    # v2 cross-function form: a helper that syncs one of its parameters,
    # called from inside a for/while loop with an accept-family argument —
    # the helper body runs (and syncs) once per iteration even though no
    # loop is lexically visible around the sync itself
    for fn in ast.walk(ctx.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if id(fn) not in ctx.callgraph.loop_called:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = (node.lineno, node.col_offset)
            if key in flagged:
                continue
            target, hit = _sync_target(node)
            if target is None or _mentions_static(target):
                continue
            # the hazard is a value flowing IN from the loop: the synced
            # root must be a parameter of the helper.  A local produced by
            # the helper itself (the engine landing (toks, lives) once per
            # decode dispatch) is the sanctioned readback, never flagged.
            root = _root_name(target)
            idx = _param_index(fn, root) if root is not None else None
            if idx is None:
                continue
            site = None
            if _mentions_accept(target):
                for s in _loop_sites_of(ctx, fn):
                    if idx < len(s.args) and not _is_hostcopy(s.args[idx]):
                        site = s
                        break
            else:
                site = _accept_at_site(ctx, fn, idx)
            if site is None:
                continue
            flagged.add(key)
            yield Finding(
                ctx.path, node.lineno, node.col_offset, RULE_ID,
                f"{TITLE}: {hit} in '{fn.name}' syncs an accept/verify-"
                f"family value once per iteration of the loop calling it "
                f"(line {site.lineno}) — land (outs, lives) with ONE "
                f"np.asarray per verify dispatch outside the loop and pass "
                f"the host copy in")

