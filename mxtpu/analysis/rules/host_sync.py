"""R001 host-sync-in-step: a host synchronization inside a jit-traced step.

``.asnumpy()`` / ``np.asarray`` / ``float()`` / ``.item()`` on a traced value
either fails at trace time (TracerArrayConversionError) or — worse, via a
shape-dependent path that concretizes — forces a device→host round trip
every step.  On the tunneled TPU runtime one readback costs a 30–100 ms
round trip (bench.py's honest-accounting note), so a single stray sync
erases the entire win of the fused step executor.  The runtime twin of this
rule is ``MXTPU_SANITIZE=transfers`` (``jax.transfer_guard`` around the
fused step).
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R001"
TITLE = "host-sync-in-step"

# attribute calls that synchronize with the host
_SYNC_METHODS = {"asnumpy", "asscalar", "item", "tolist", "block_until_ready",
                 "wait_to_read", "wait_to_write"}
# module functions that materialize on the host
_SYNC_FUNCS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
               "np.copy", "numpy.copy", "jax.device_get", "device_get"}
# builtins that concretize a traced value
_CONCRETIZERS = {"float", "int", "bool"}
# names whose presence in the argument marks a static (python-int) quantity:
# int(x.shape[0]) / float(len(xs)) trace fine and are not host syncs
_STATIC_HINTS = {"shape", "ndim", "size", "len", "range", "dtype", "dims"}


def _mentions_static(node) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_HINTS:
            return True
        if isinstance(n, ast.Call) and (dotted_name(n.func) or "") == "len":
            return True
    return False


def check(ctx):
    seen = set()
    for fn in ctx.step_functions:
        # v2: step_functions is closed over the call graph (self-method,
        # alias, lax HOF edges); name the drag-in chain for transitive hits
        path = ctx.callgraph.trace_path(fn)
        via = f" (traced via {' -> '.join(path)})" if len(path) > 1 else ""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            name = dotted_name(node.func)
            hit = None
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS:
                hit = f".{node.func.attr}()"
            elif name in _SYNC_FUNCS:
                if node.args and not isinstance(node.args[0], ast.Constant):
                    hit = f"{name}()"
            elif name in _CONCRETIZERS and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant) \
                    and not _mentions_static(node.args[0]):
                hit = f"{name}()"
            if hit:
                seen.add(key)
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"{TITLE}: {hit} inside a function that flows into a jax "
                    f"trace (jit/grad) forces a host sync every step — read "
                    f"results outside the step, or keep the value traced"
                    f"{via}")
