"""R008 unbounded-map: per-request dict growth with no eviction site.

A serving-plane object that writes ``self.x[req.id] = ...`` on every
request grows without bound unless *something* in the same class pops the
entry when the request retires — the classic slow leak that only shows up
as OOM after days of traffic. The SLO scheduler's ``_inflight`` map
(``mxtpu.sched.policy``) is exactly this shape done right: ``register``
grows it, ``forget`` pops it; delete the pop and the scheduler leaks one
entry per request forever while every test still passes.

Flagged: inside a class, a subscript store onto a ``self`` attribute
(outside ``__init__``) whose key smells like a request identity
(``something.id`` / ``something.rid`` / ``request_id``-style names) or
whose attribute name itself hints at per-request/per-tenant tracking
(``inflight`` / ``request`` / ``per_req``), when the class body contains
NO shrink site for that attribute.

Blessed (any one of these in the same class clears the attribute):

* ``self.x.pop(...)`` / ``self.x.popitem()`` / ``self.x.clear()``;
* ``del self.x[...]``;
* rebinding ``self.x = ...`` outside ``__init__`` (periodic reset);
* bounded-by-construction stores — key the dict by tenant/config and cap
  it (as ``metrics.record_tenant`` does), then suppress with
  ``# mxtpu: ignore[R008]`` and say why.
"""

from __future__ import annotations

import ast

from ..lint import Finding

RULE_ID = "R008"
TITLE = "unbounded-map"

# key expressions that smell like a per-request identity
_KEY_ATTRS = {"id", "rid", "request_id", "req_id"}
_KEY_NAMES = {"rid", "request_id", "req_id"}
# attribute names that declare per-request/per-tenant intent outright
_NAME_HINTS = ("inflight", "in_flight", "request", "per_req", "per_tenant")

_SHRINK_METHODS = {"pop", "popitem", "clear"}


def _self_attr(node) -> str:
    """``self.x`` -> ``'x'``, else ''."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _key_is_request_like(key) -> bool:
    if isinstance(key, ast.Attribute) and key.attr in _KEY_ATTRS:
        return True
    return isinstance(key, ast.Name) and key.id in _KEY_NAMES


def _method_of(cls: ast.ClassDef, node, ctx):
    """Nearest enclosing function of ``node`` that is a direct method of
    ``cls`` (None for class-level / nested-beyond-method code)."""
    fn = None
    for a in ctx.ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = a
        if a is cls:
            return fn
    return None


def _shrunk_attrs(cls: ast.ClassDef, ctx) -> set:
    """Self attributes the class body ever shrinks (pop/clear/del/rebind
    outside __init__)."""
    out = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SHRINK_METHODS:
            attr = _self_attr(node.func.value)
            if attr:
                out.add(attr)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr:
                        out.add(attr)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    fn = _method_of(cls, node, ctx)
                    if fn is not None and fn.name != "__init__":
                        out.add(attr)     # periodic reset counts as a bound
    return out


def check(ctx):
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        shrunk = _shrunk_attrs(cls, ctx)
        seen = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Subscript):
                    continue
                attr = _self_attr(t.value)
                if not attr or attr in shrunk or attr in seen:
                    continue
                named = any(h in attr.lower() for h in _NAME_HINTS)
                if not (named or _key_is_request_like(t.slice)):
                    continue
                fn = _method_of(cls, node, ctx)
                if fn is None:
                    continue
                seen.add(attr)
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"{TITLE}: `self.{attr}[...]` grows per request but "
                    f"class `{cls.name}` never pops/clears/rebinds it — "
                    f"one leaked entry per request until OOM; evict on "
                    f"retire (pop in the forget/retire path) or cap and "
                    f"suppress with a reason")
