"""R003 untracked-nondeterminism: host randomness / wall-clock in a traced
step.

``np.random.*`` or ``time.time()`` inside a jit-traced function is baked in
as a CONSTANT at trace time: every subsequent step replays the same "random"
draw (silently wrong dropout/sampling), and a checkpoint-resumed run can
never replay the stream.  The framework's answer is ``mxtpu.rng``: keys ride
as traced arguments (``rng.next_key()`` inside the step splits from a traced
base key), so stochastic ops differ per step AND resume bit-exactly
(``rng.get/set_state_blob``).
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R003"
TITLE = "untracked-nondeterminism"

_CLOCK_FUNCS = {"time.time", "time.time_ns", "time.perf_counter",
                "time.perf_counter_ns", "time.monotonic",
                "datetime.now", "datetime.utcnow",
                "datetime.datetime.now", "datetime.datetime.utcnow"}
_RANDOM_MODULE_FUNCS = {"random", "randint", "randrange", "uniform", "gauss",
                        "normalvariate", "choice", "choices", "sample",
                        "shuffle", "betavariate", "expovariate"}


def check(ctx):
    seen = set()
    for fn in ctx.step_functions:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            name = dotted_name(node.func) or ""
            hit = None
            if name.startswith(("np.random.", "numpy.random.")):
                hit = name
            elif name.startswith("random.") \
                    and name.split(".", 1)[1] in _RANDOM_MODULE_FUNCS:
                hit = name
            elif name in _CLOCK_FUNCS:
                hit = name
            if hit:
                seen.add(key)
                fix = ("draw from mxtpu.rng (keys ride as traced args and "
                       "resume bit-exactly)" if "random" in hit
                       else "hoist the clock read out of the step and pass "
                            "it as a traced argument")
                yield Finding(
                    ctx.path, node.lineno, node.col_offset, RULE_ID,
                    f"{TITLE}: {hit}() inside a traced step is baked in as a "
                    f"constant at trace time (same value every step, not "
                    f"replayable after checkpoint resume) — {fix}")
