"""R005 overbroad-except: a handler that swallows KeyboardInterrupt /
latched producer errors.

A bare ``except:`` (or ``except BaseException:`` that neither re-raises nor
binds-and-uses the error) eats ``KeyboardInterrupt`` and ``SystemExit`` —
and in this codebase's producer/writer threads it also eats the error the
consumer is waiting to re-raise (DeviceFeed latches producer exceptions;
the checkpoint writer queues them for the next ``save()``).  A swallowed
producer error turns a crash into a silent hang.  Handlers that latch the
exception (``except BaseException as e: job.error = e``) or re-raise
(``raise``) are the blessed patterns and are not flagged.
"""

from __future__ import annotations

import ast

from ..lint import Finding, dotted_name

RULE_ID = "R005"
TITLE = "overbroad-except"


def _catches_base(handler) -> bool:
    t = handler.type
    if t is None:
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        name = dotted_name(e) or ""
        if name.rsplit(".", 1)[-1] in ("BaseException", "KeyboardInterrupt",
                                       "SystemExit", "GeneratorExit"):
            # catching KeyboardInterrupt/SystemExit on purpose and dropping
            # them is the same hazard as BaseException
            return True
    return False


def _handler_reraises(handler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
    return False


def _handler_uses_binding(handler) -> bool:
    if handler.name is None:
        return False
    for n in ast.walk(handler):
        if isinstance(n, ast.Name) and n.id == handler.name \
                and isinstance(n.ctx, ast.Load):
            return True
    return False


def check(ctx):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _catches_base(handler):
                continue
            if _handler_reraises(handler) or _handler_uses_binding(handler):
                continue
            what = "bare except:" if handler.type is None else \
                f"except {ast.unparse(handler.type)}:"
            yield Finding(
                ctx.path, handler.lineno, handler.col_offset, RULE_ID,
                f"{TITLE}: {what} swallows KeyboardInterrupt/SystemExit (and "
                f"any latched producer error) — catch Exception, re-raise, "
                f"or latch the bound error for the consumer")
