"""R002 donation-use-after-pass: reading a name after passing it at a
donated argnum.

``jax.jit(fn, donate_argnums=…)`` transfers buffer ownership: on accelerator
backends the donated device array is DELETED when the compiled program runs,
and any later read dies with XLA's opaque "Array has been deleted".  This is
the exact shape of the PR 2 snapshot bug: the async checkpoint held device
references that the next fused step's donation invalidated.  The runtime
twin is ``MXTPU_SANITIZE=donation`` (poisoned donated references raise a
named error on CPU too, where XLA silently skips donation).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..lint import Finding, dotted_name

RULE_ID = "R002"
TITLE = "donation-use-after-pass"


def _donated_indices(call: ast.Call) -> Optional[List[int]]:
    """Constant donate_argnums of a jit-like call, else None."""
    name = dotted_name(call.func) or ""
    if name.rsplit(".", 1)[-1] not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return [e.value for e in v.elts]
        return None          # computed argnums: can't map positions
    return None


def _scopes(tree):
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield n


def _pos(node) -> Tuple[int, int]:
    return (node.lineno, node.col_offset)


def _end(node) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", node.col_offset))


def check(ctx):
    # pass 1 (whole module): names bound to a donating jit program
    donated_fns: Dict[str, List[int]] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            idxs = _donated_indices(n.value)
            if idxs is not None:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        donated_fns[t.id] = idxs

    # pass 2 (per scope): donated calls vs later loads of the passed names
    for scope in _scopes(ctx.tree):
        body = scope.body if not isinstance(scope, ast.Lambda) else [scope.body]
        calls: List[Tuple[ast.Call, List[str]]] = []
        loads: Dict[str, List[Tuple[int, int]]] = {}
        stores: Dict[str, List[Tuple[int, int]]] = {}
        own_funcs = set()

        def walk_scope(nodes):
            for stmt in nodes:
                for n in ast.walk(stmt):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)) and n is not stmt:
                        own_funcs.add(id(n))
                    if any(id(a) in own_funcs for a in ctx.ancestors(n)):
                        continue          # nested scope: analyzed separately
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        own_funcs.add(id(n))
                        continue
                    if isinstance(n, ast.Call):
                        idxs = None
                        if isinstance(n.func, ast.Name) \
                                and n.func.id in donated_fns:
                            idxs = donated_fns[n.func.id]
                        elif isinstance(n.func, ast.Call):
                            idxs = _donated_indices(n.func)
                        if idxs:
                            names = [a.id for i, a in enumerate(n.args)
                                     if i in idxs and isinstance(a, ast.Name)]
                            if names:
                                calls.append((n, names))
                    if isinstance(n, ast.Name):
                        tgt = loads if isinstance(n.ctx, ast.Load) else stores
                        tgt.setdefault(n.id, []).append(_pos(n))

        walk_scope(body)

        for call, names in calls:
            callpos = _end(call)
            # the statement holding the call: its assign targets rebind the
            # name at the call itself (x = f(x) is the blessed pattern)
            stmt = ctx.parent(call)
            while stmt is not None and not isinstance(stmt, ast.stmt):
                stmt = ctx.parent(stmt)
            rebound_here = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            rebound_here.add(n.id)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(stmt.target, ast.Name):
                rebound_here.add(stmt.target.id)

            enclosing_loop = next(
                (a for a in ctx.ancestors(call)
                 if isinstance(a, (ast.For, ast.While, ast.AsyncFor))), None)

            for name in names:
                if name in rebound_here:
                    continue
                next_store = min(
                    (p for p in stores.get(name, []) if p > callpos),
                    default=(1 << 30, 0))
                bad = [p for p in loads.get(name, [])
                       if callpos < p < next_store
                       and not (_pos(call) <= p <= callpos)]
                if bad:
                    line, col = bad[0]
                    yield Finding(
                        ctx.path, line, col, RULE_ID,
                        f"{TITLE}: '{name}' was passed at a donated argnum "
                        f"on line {call.lineno} — its buffer is deleted on "
                        f"accelerators; rebind the name to the program's "
                        f"output before reading it again")
                elif enclosing_loop is not None:
                    loop_stores = [
                        n for n in ast.walk(enclosing_loop)
                        if isinstance(n, ast.Name) and n.id == name
                        and isinstance(n.ctx, ast.Store)
                        and not any(id(a) in own_funcs
                                    for a in ctx.ancestors(n))]
                    if not loop_stores:
                        yield Finding(
                            ctx.path, call.lineno, call.col_offset, RULE_ID,
                            f"{TITLE}: '{name}' is passed at a donated "
                            f"argnum inside a loop but never rebound — the "
                            f"next iteration re-passes a deleted buffer")
