"""R002 donation-use-after-pass: reading a name after passing it at a
donated argnum.

``jax.jit(fn, donate_argnums=…)`` transfers buffer ownership: on accelerator
backends the donated device array is DELETED when the compiled program runs,
and any later read dies with XLA's opaque "Array has been deleted".  This is
the exact shape of the PR 2 snapshot bug: the async checkpoint held device
references that the next fused step's donation invalidated.  The runtime
twin is ``MXTPU_SANITIZE=donation`` (poisoned donated references raise a
named error on CPU too, where XLA silently skips donation).

v2 (dataflow port): post-donation reads are found by walking the scope's CFG
(:meth:`mxtpu.analysis.dataflow.CFG.uses_after`), so

* a read on only *one* branch after the donating call is caught, and a read
  on a path where the name was already rebound is **not** (v1's positional
  scan flagged loads by line order alone);
* the loop form falls out of the same query: the loop back edge re-reaches
  the donating call's own argument load, which is exactly "next iteration
  re-passes a deleted buffer";
* donated program handles bound to attributes (``self._step = jax.jit(pure,
  donate_argnums=…)`` in a builder method, called as ``self._step(params,…)``
  somewhere else — the cross-function PR 2 shape) are tracked by dotted
  name, not just local ``Name`` bindings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..lint import Finding, dotted_name

RULE_ID = "R002"
TITLE = "donation-use-after-pass"


def _donated_indices(call: ast.Call) -> Optional[List[int]]:
    """Constant donate_argnums of a jit-like call, else None."""
    name = dotted_name(call.func) or ""
    if name.rsplit(".", 1)[-1] not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return [v.value]
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return [e.value for e in v.elts]
        return None          # computed argnums: can't map positions
    return None


def _scopes(tree):
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def _pos(node) -> Tuple[int, int]:
    return (node.lineno, node.col_offset)


def _end(node) -> Tuple[int, int]:
    return (getattr(node, "end_lineno", node.lineno),
            getattr(node, "end_col_offset", node.col_offset))


def _owned_by(ctx, node, scope) -> bool:
    """Is ``node`` evaluated by ``scope`` itself (not a nested function)?"""
    for a in ctx.ancestors(node):
        if a is scope:
            return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
    return scope is ctx.tree


def check(ctx):
    # pass 1 (whole module): callables bound to a donating jit program —
    # plain names (step = jax.jit(...)) and dotted handles
    # (self._step = jax.jit(...)), the cross-method form
    donated_fns: Dict[str, List[int]] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            idxs = _donated_indices(n.value)
            if idxs is not None:
                for t in n.targets:
                    key = t.id if isinstance(t, ast.Name) else dotted_name(t)
                    if key:
                        donated_fns[key] = idxs

    # pass 2 (per scope): donated calls vs reachable post-donation reads
    for scope in _scopes(ctx.tree):
        cfg = ctx.callgraph.cfg(scope)
        calls: List[Tuple[ast.Call, List[ast.expr]]] = []
        for n in ast.walk(scope):
            if not isinstance(n, ast.Call) or not _owned_by(ctx, n, scope):
                continue
            idxs = None
            callee = dotted_name(n.func)
            if callee and callee in donated_fns:
                idxs = donated_fns[callee]
            elif isinstance(n.func, ast.Call):
                idxs = _donated_indices(n.func)   # jit(f, donate...)(x)
            if idxs:
                args = [a for i, a in enumerate(n.args) if i in idxs]
                if args:
                    calls.append((n, args))

        # dotted-name loads/stores for attribute-valued donated args
        # (self.params re-read after donation) — positional, v1 style
        attr_loads: Dict[str, List[Tuple[int, int]]] = {}
        attr_stores: Dict[str, List[Tuple[int, int]]] = {}
        for n in ast.walk(scope):
            if isinstance(n, ast.Attribute) and _owned_by(ctx, n, scope):
                d = dotted_name(n)
                if d is None:
                    continue
                tgt = attr_loads if isinstance(n.ctx, ast.Load) else attr_stores
                tgt.setdefault(d, []).append(_pos(n))

        for call, args in calls:
            stmt = cfg.carrier(call)
            callpos = _end(call)
            enclosing_loop = next(
                (a for a in ctx.ancestors(call)
                 if isinstance(a, (ast.For, ast.While, ast.AsyncFor))), None)
            for arg in args:
                if isinstance(arg, ast.Name):
                    name = arg.id
                    if stmt is None:
                        continue
                    hits = cfg.uses_after(stmt, name)
                    # a hit that is the donating call's own argument load is
                    # the back edge: the loop never rebound the name
                    own_arg_ids = {id(arg)}
                    loop_hits = [h for h in hits if id(h) in own_arg_ids]
                    flow_hits = [h for h in hits if id(h) not in own_arg_ids]
                    if flow_hits:
                        h = flow_hits[0]
                        yield Finding(
                            ctx.path, h.lineno, h.col_offset, RULE_ID,
                            f"{TITLE}: '{name}' was passed at a donated "
                            f"argnum on line {call.lineno} — its buffer is "
                            f"deleted on accelerators; rebind the name to "
                            f"the program's output before reading it again")
                    elif loop_hits or (
                            enclosing_loop is not None and not hits
                            and not any(d.name == name for d in
                                        _stmt_bindings(stmt))
                            and not _rebound_in(ctx, enclosing_loop, name,
                                                scope)):
                        yield Finding(
                            ctx.path, call.lineno, call.col_offset, RULE_ID,
                            f"{TITLE}: '{name}' is passed at a donated "
                            f"argnum inside a loop but never rebound — the "
                            f"next iteration re-passes a deleted buffer")
                else:
                    d = dotted_name(arg)
                    if not d:
                        continue
                    next_store = min(
                        (p for p in attr_stores.get(d, []) if p > callpos),
                        default=(1 << 30, 0))
                    bad = [p for p in attr_loads.get(d, [])
                           if callpos < p < next_store]
                    if bad:
                        line, col = bad[0]
                        yield Finding(
                            ctx.path, line, col, RULE_ID,
                            f"{TITLE}: '{d}' was passed at a donated argnum "
                            f"on line {call.lineno} — its buffer is deleted "
                            f"on accelerators; rebind it to the program's "
                            f"output before reading it again")
                    elif enclosing_loop is not None and not [
                            p for p in attr_stores.get(d, [])
                            if _pos(enclosing_loop) <= p]:
                        yield Finding(
                            ctx.path, call.lineno, call.col_offset, RULE_ID,
                            f"{TITLE}: '{d}' is passed at a donated argnum "
                            f"inside a loop but never rebound — the next "
                            f"iteration re-passes a deleted buffer")


def _stmt_bindings(stmt):
    from ..dataflow import bindings_of
    return bindings_of(stmt) if stmt is not None else []


def _rebound_in(ctx, loop, name, scope) -> bool:
    for n in ast.walk(loop):
        if isinstance(n, ast.Name) and n.id == name \
                and isinstance(n.ctx, ast.Store) and _owned_by(ctx, n, scope):
            return True
    return False
