"""Module-level call graph with traced-context propagation for tpulint v2.

v1's ``ModuleContext.step_functions`` chased plain-``Name`` calls only, so a
fused step calling ``self._loss(...)``, an aliased helper (``h = helper``),
or a ``lax.scan`` body was outside the traced set — a helper that does
``float(x)`` two frames down was invisible to R001.  This module builds an
explicit call graph:

* **edges** — ``Name`` calls (lexically resolved, innermost scope wins, same
  as v1), ``self.m()``/``cls.m()`` calls (resolved to methods of the caller's
  enclosing class), and local aliases resolved through the reaching-definition
  engine (:meth:`~mxtpu.analysis.dataflow.CFG.binds_value`).
* **traced set** — seeded from jit/grad/vmap decorators and trace-entry calls
  (as v1), plus function-valued arguments of jax control-flow HOFs
  (``lax.scan``/``while_loop``/``cond``/…, which trace their bodies exactly
  like ``jit`` traces its argument), then closed over the edges.  Each traced
  function remembers the call chain that dragged it in, so findings can print
  ``step -> helper -> helper2``.
* **loop-called set** — functions whose body runs inside a ``for``/``while``
  iteration of some caller (directly at a loop call site, or transitively
  through the graph).  R009's per-token host-sync rule uses it to catch the
  helper form: ``for t in ...: consume(accept)`` where ``consume`` does the
  ``.item()``.

Pure ``ast``; no jax import at lint time.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .dataflow import CFG
from . import lint as _lint

__all__ = ["CallGraph"]

# jax higher-order control flow: every function-valued argument is traced
_TRACE_HOF_NAMES = {"scan", "while_loop", "fori_loop", "cond", "switch",
                    "associative_scan", "checkpoint", "remat", "custom_root",
                    "custom_linear_solve"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class CallGraph:
    """Call graph + traced/loop contexts for one :class:`ModuleContext`."""

    def __init__(self, ctx):
        self.ctx = ctx
        self._cfgs: Dict[int, CFG] = {}
        # id(caller scope) -> [(callee FunctionDef, call site)]
        self.edges: Dict[int, List[Tuple[ast.AST, ast.Call]]] = {}
        # id(fn) -> (parent fn or None, call/seed site) for message paths
        self._trace_parent: Dict[int, Tuple[Optional[ast.AST], ast.AST]] = {}
        self._traced: Optional[List[ast.AST]] = None
        self._loop_called: Optional[Dict[int, Tuple[ast.AST, ast.Call]]] = None
        self._class_of: Dict[int, ast.ClassDef] = {}
        self._build()

    # -- plumbing -----------------------------------------------------------
    def cfg(self, scope) -> CFG:
        c = self._cfgs.get(id(scope))
        if c is None:
            c = self._cfgs[id(scope)] = CFG(scope)
        return c

    def _enclosing_class(self, fn) -> Optional[ast.ClassDef]:
        cid = self._class_of.get(id(fn))
        if cid is not None:
            return cid
        for a in self.ctx.ancestors(fn):
            if isinstance(a, _FUNC_NODES):
                return None                  # nested def, not a method
            if isinstance(a, ast.ClassDef):
                self._class_of[id(fn)] = a
                return a
        return None

    def _methods(self, cls: ast.ClassDef, name: str) -> List[ast.AST]:
        return [n for n in cls.body
                if isinstance(n, _FUNC_NODES) and n.name == name]

    def _resolve_callable(self, expr, at_node, caller) -> List[ast.AST]:
        """Resolve a callable expression at a use site to FunctionDef nodes.

        Order: lexical (v1 semantics — innermost visible scope, so a traced
        inner ``def step`` never drags in a same-named eager method), then
        ``self.m``/``cls.m`` against the caller's class, then a single
        unambiguous local alias via reaching definitions."""
        if isinstance(expr, ast.Name):
            fns = self.ctx.resolve_function(expr.id, at_node)
            if fns:
                return fns
            scope = self.ctx.enclosing_scope(at_node)
            if isinstance(scope, _FUNC_NODES + (ast.Module,)):
                value = self.cfg(scope).binds_value(expr.id, at_node)
                if isinstance(value, _FUNC_NODES):
                    return [value]
                if isinstance(value, ast.Name) and value.id != expr.id:
                    return self.ctx.resolve_function(value.id, value)
            return []
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and caller is not None:
            cls = self._enclosing_class(caller)
            if cls is not None:
                return self._methods(cls, expr.attr)
        return []

    # -- graph construction -------------------------------------------------
    def _build(self):
        ctx = self.ctx
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            caller = ctx.enclosing_scope(call)
            caller_fn = caller if isinstance(caller, _FUNC_NODES) else None
            callees = self._resolve_callable(call.func, call, caller_fn)
            if callees:
                self.edges.setdefault(id(caller), []).extend(
                    (c, call) for c in callees)

    # -- traced set ---------------------------------------------------------
    def _seeds(self) -> List[Tuple[ast.AST, ast.AST]]:
        """(fn, seed site) pairs that enter a jax trace directly."""
        ctx = self.ctx
        seeds: List[Tuple[ast.AST, ast.AST]] = []
        for n in ast.walk(ctx.tree):
            if isinstance(n, _FUNC_NODES):
                for dec in n.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _lint._is_trace_entry(target):
                        seeds.append((n, dec))
                    elif isinstance(dec, ast.Call) and dec.args \
                            and _lint._is_trace_entry(dec.args[0]):
                        seeds.append((n, dec))   # @partial(jax.jit, ...)
            elif isinstance(n, ast.Call):
                caller = ctx.enclosing_scope(n)
                caller_fn = caller if isinstance(caller, _FUNC_NODES) else None
                name = _lint.dotted_name(n.func)
                last = name.rsplit(".", 1)[-1] if name else None
                if _lint._is_trace_entry(n.func) and n.args:
                    for fn in self._resolve_callable(n.args[0], n, caller_fn):
                        seeds.append((fn, n))
                elif last in _TRACE_HOF_NAMES:
                    for arg in n.args:
                        for fn in self._resolve_callable(arg, n, caller_fn):
                            seeds.append((fn, n))
        return seeds

    @property
    def traced_functions(self) -> List[ast.AST]:
        """Functions that run under a jax trace, closed over call edges and
        nested defs.  Order: seeds first, then discovery order."""
        if self._traced is not None:
            return self._traced
        traced: Dict[int, ast.AST] = {}
        for fn, site in self._seeds():
            if id(fn) not in traced:
                traced[id(fn)] = fn
                self._trace_parent[id(fn)] = (None, site)
        work = list(traced.values())
        while work:
            f = work.pop(0)
            # nested defs trace with their parent
            for n in ast.walk(f):
                if isinstance(n, _FUNC_NODES) and n is not f \
                        and id(n) not in traced:
                    traced[id(n)] = n
                    self._trace_parent[id(n)] = (f, n)
                    work.append(n)
            for callee, site in self.edges.get(id(f), ()):
                if id(callee) not in traced:
                    traced[id(callee)] = callee
                    self._trace_parent[id(callee)] = (f, site)
                    work.append(callee)
        self._traced = list(traced.values())
        return self._traced

    def trace_path(self, fn) -> List[str]:
        """Call chain from a trace seed to ``fn``, e.g. ``['step', 'helper',
        'helper2']`` — empty if ``fn`` is not traced."""
        _ = self.traced_functions            # force closure computation
        if id(fn) not in self._trace_parent:
            return []
        path: List[str] = []
        cur: Optional[ast.AST] = fn
        guard = 0
        while cur is not None and guard < 64:
            guard += 1
            path.append(getattr(cur, "name", "<lambda>"))
            cur = self._trace_parent.get(id(cur), (None, None))[0]
        return list(reversed(path))

    # -- loop context -------------------------------------------------------
    def _in_loop(self, node, within) -> bool:
        """Is ``node`` lexically inside a for/while of ``within`` (not hidden
        behind a nested function boundary)?"""
        for a in self.ctx.ancestors(node):
            if a is within:
                return False
            if isinstance(a, _FUNC_NODES + (ast.Lambda,)):
                return False
            if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
                return True
        return False

    @property
    def loop_called(self) -> Dict[int, Tuple[ast.AST, ast.Call]]:
        """id(fn) -> (fn, loop call site): functions whose body executes per
        loop iteration of some caller, transitively."""
        if self._loop_called is not None:
            return self._loop_called
        out: Dict[int, Tuple[ast.AST, ast.Call]] = {}
        work: List[ast.AST] = []
        for pairs in self.edges.values():
            for callee, site in pairs:
                scope = self.ctx.enclosing_scope(site)
                if self._in_loop(site, scope) and id(callee) not in out:
                    out[id(callee)] = (callee, site)
                    work.append(callee)
        while work:
            f = work.pop(0)
            for callee, site in self.edges.get(id(f), ()):
                if id(callee) not in out:
                    out[id(callee)] = (callee, site)
                    work.append(callee)
        self._loop_called = out
        return out
