"""CLI: ``python -m mxtpu.analysis <path>...`` — run tpulint.

Exit status: 0 clean, 1 findings, 2 usage error.  ``--select``/``--ignore``
filter rules; ``--list-rules`` prints the catalog; ``--stats`` appends a
per-rule count summary.  The tier-1 guard
(``tests/test_analysis_guard.py``) runs ``python -m mxtpu.analysis mxtpu/``
and asserts exit 0 — the committed tree stays self-lint-clean.
"""

from __future__ import annotations

import argparse
import sys

from .lint import lint_paths
from . import rules as rules_pkg


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxtpu.analysis",
        description="tpulint: static checker for mxtpu's donation, "
                    "host-sync, retrace, and thread-ownership contracts")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE", help="only run these rule ids")
    parser.add_argument("--ignore", action="append", default=None,
                        metavar="RULE", help="skip these rule ids")
    parser.add_argument("--stats", action="store_true",
                        help="append a per-rule finding count summary")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for mod in rules_pkg.RULES:
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{mod.RULE_ID}  {mod.TITLE:<40s} {doc}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, select=args.select, ignore=args.ignore)
    for f in findings:
        print(f.format())
    if args.stats:
        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        for rule in sorted(counts):
            print(f"{rule}: {counts[rule]} finding(s)")
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
