"""CLI: ``python -m mxtpu.analysis <path>...`` — run tpulint (and the
program auditor).

Exit status: 0 clean, 1 findings, 2 usage error.  ``--select``/``--ignore``
filter rules; ``--list-rules`` prints the catalog; ``--stats`` appends a
per-rule count summary.  ``--format json`` emits one machine-readable JSON
document; ``--baseline FILE`` switches to ratchet mode (exit 1 only on
findings *beyond* the recorded per-(path, rule) counts; write the file with
``--write-baseline``).  ``--audit`` runs the jaxpr-level program auditor
over the canonical compiled programs instead of linting paths;
``--audit --expect-fail`` proves each audit invariant by seeding one
violation per class and requiring its detection.  The tier-1 guards
(``tests/test_analysis_guard.py``, ``tests/test_audit_guard.py``) run
``python -m mxtpu.analysis mxtpu tests bench.py`` and ``--audit`` and
assert exit 0 — the committed tree stays self-lint- and audit-clean.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List

from .lint import Finding, lint_paths
from . import rules as rules_pkg


def _counts(findings) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return counts


def _baseline_counts(findings) -> Dict[str, int]:
    """Per-(path, rule) finding counts, keyed ``"path::rule"``."""
    out: Dict[str, int] = {}
    for f in findings:
        key = f"{f.path}::{f.rule}"
        out[key] = out.get(key, 0) + 1
    return out


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "counts" in doc:
        doc = doc["counts"]
    if not isinstance(doc, dict):
        raise ValueError(f"baseline {path}: expected a JSON object")
    return {str(k): int(v) for k, v in doc.items()}


def diff_baseline(findings: List[Finding],
                  baseline: Dict[str, int]) -> List[Finding]:
    """Findings beyond the baseline's per-(path, rule) budget.  Count-based
    on purpose: line numbers shift on every edit, so anchoring the ratchet
    to positions would churn; a (path, rule) count only moves when a finding
    is truly added or removed."""
    new: List[Finding] = []
    budget = dict(baseline)
    for f in findings:                       # findings arrive sorted
        key = f"{f.path}::{f.rule}"
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(f)
    return new


def _json_doc(findings: List[Finding], new: List[Finding] = None) -> dict:
    def enc(f: Finding) -> dict:
        return {"path": f.path, "line": f.line, "col": f.col,
                "rule": f.rule, "message": f.message}
    doc = {"version": 2,
           "findings": [enc(f) for f in findings],
           "counts": _counts(findings)}
    if new is not None:
        doc["new_findings"] = [enc(f) for f in new]
    return doc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mxtpu.analysis",
        description="tpulint: static checker for mxtpu's donation, "
                    "host-sync, retrace, and thread-ownership contracts — "
                    "plus the jaxpr-level program auditor (--audit)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE", help="only run these rule ids")
    parser.add_argument("--ignore", action="append", default=None,
                        metavar="RULE", help="skip these rule ids")
    parser.add_argument("--stats", action="store_true",
                        help="append a per-rule finding count summary")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="output format (default: text)")
    parser.add_argument("--baseline", metavar="FILE", default=None,
                        help="ratchet mode: exit nonzero only on findings "
                             "beyond this baseline's per-(path, rule) counts")
    parser.add_argument("--write-baseline", metavar="FILE", default=None,
                        help="write the current per-(path, rule) counts as a "
                             "baseline file and exit 0")
    parser.add_argument("--audit", action="store_true",
                        help="run the program auditor (shardcheck, "
                             "collective budgets, retrace closure) over the "
                             "canonical compiled programs")
    parser.add_argument("--expect-fail", action="store_true",
                        help="with --audit: seed one violation per invariant "
                             "class and require each to be detected")
    args = parser.parse_args(argv)

    if args.list_rules:
        for mod in rules_pkg.RULES:
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{mod.RULE_ID}  {mod.TITLE:<40s} {doc}")
        from . import audit as audit_mod
        for rid, title, blurb in audit_mod.rule_catalog():
            print(f"{rid}  {title:<40s} {blurb}")
        return 0

    if args.audit:
        from . import audit as audit_mod
        return audit_mod.main_audit(expect_fail=args.expect_fail,
                                    fmt=args.format,
                                    select=args.select, ignore=args.ignore)
    if args.expect_fail:
        parser.print_usage(sys.stderr)
        print("error: --expect-fail requires --audit", file=sys.stderr)
        return 2
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    findings = lint_paths(args.paths, select=args.select, ignore=args.ignore)

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as fh:
            json.dump({"version": 2, "counts": _baseline_counts(findings)},
                      fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(f"baseline: {len(findings)} finding(s) across "
              f"{len(_baseline_counts(findings))} (path, rule) key(s) -> "
              f"{args.write_baseline}")
        return 0

    new = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        new = diff_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps(_json_doc(findings, new), indent=1, sort_keys=True))
    else:
        shown = findings if new is None else new
        for f in shown:
            print(f.format())
        if args.stats:
            for rule, cnt in sorted(_counts(shown).items()):
                print(f"{rule}: {cnt} finding(s)")

    if new is not None:
        if new:
            print(f"{len(new)} new finding(s) beyond baseline "
                  f"({len(findings)} total)", file=sys.stderr)
            return 1
        return 0
    if findings:
        if args.format != "json":
            print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
