"""The jaxpr-level program auditor (``python -m mxtpu.analysis --audit``).

tpulint (``lint.py``) reads source; the auditor reads PROGRAMS.  It builds
the framework's canonical compiled programs — the fused training step
(``step_cache.StepExecutor``), the ZeRO bucketed update
(``parallel/zero.py``), and the serving decode/verify/prefill family
(``serving/kv.py``), including the sharded fsdp×tp decode — abstractly, on
a virtual 8-device CPU mesh, and statically verifies the invariants the
incident history says drift silently:

* **shardcheck** (A101/A102/A103/A104) — the SpecLayout/ServingLayout
  tables against the mesh and the canonical parameter geometry: an axis a
  spec names must exist (A101), a sharded probe dim must divide cleanly
  instead of silently degrading to replicated (A102), ``compose_spec`` may
  only ever insert the fsdp axis on dim 0 — contraction-dim sharding
  reorders float reductions, the PR 8 ban (A103) — and the serving
  row-parallel pair must replicate, the PR 19 bit-exactness precondition
  (A104);
* **collective / transfer budgets** (A201/A202) — compiled-HLO collective
  counts against per-program budgets (the sharded decode compiles with
  ZERO all-reduce or greedy token parity is already gone; the ZeRO update
  must gather, never all-reduce) and a jaxpr walk proving no host
  callback/transfer primitive rides a hot program;
* **retrace closure** (A301) — the engine's ProgramCache key functions
  (``serving/engine.py::audit_key_specs``) evaluated over the whole
  admissible request domain: every key component must take a bounded set
  of values, so the program count is provably finite (the trace-once
  contract as a theorem instead of a counter assertion).

``--expect-fail`` seeds one violation per invariant class and requires its
detection — the auditor proves it can still see each failure mode, not
just that today's tree is clean.  Findings reuse :class:`lint.Finding`
with ``<audit:...>`` paths so ``--select``/``--ignore``/``--format json``
work unchanged.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .lint import Finding

# -- rule catalog -----------------------------------------------------------

_CATALOG = [
    ("A101", "spec-axis-missing",
     "layout spec names a mesh axis the audit mesh does not have"),
    ("A102", "spec-dim-indivisible",
     "sharded table dim does not divide by its mesh axes (silent degrade)"),
    ("A103", "contraction-dim-shard",
     "spec composition shards a contraction (non-0) dim — PR 8 ban"),
    ("A104", "row-parallel-not-replicated",
     "serving row-parallel pair must be P() for bit-exactness — PR 19"),
    ("A201", "collective-budget-exceeded",
     "compiled program's collective counts violate its budget"),
    ("A202", "host-transfer-in-program",
     "host callback/transfer primitive inside a compiled program"),
    ("A301", "open-program-key-set",
     "program-cache key component unbounded over the request domain"),
]


def rule_catalog():
    return list(_CATALOG)


# seed name -> (rule it must trip, which legs to run)
_SEEDS: List[Tuple[str, str, Tuple[str, ...]]] = [
    ("spec_axis", "A101", ("shardcheck",)),
    ("contraction_shard", "A103", ("shardcheck",)),
    ("row_parallel", "A104", ("shardcheck",)),
    ("extra_collective", "A201", ("serving",)),
    ("host_transfer", "A202", ("serving",)),
    ("open_keys", "A301", ("keys",)),
]

_MIN_DEVICES = 8
_LEGS = ("shardcheck", "serving", "zero", "fused_step", "keys")

# canonical audit geometry: tiny transformer with a DIVISIBLE vocab (the
# guard tests use vocab 50 to exercise filter_spec degradation; the audit
# wants the clean-shard case so A102 is meaningful), 4 slots on a (4, 2)
# fsdp×tp mesh
_VOCAB, _SLOTS, _TOT, _CHUNK, _K = 64, 4, 64, 4, 4
_MAX_LEN, _PREFILL_CHUNK = 256, 16


def _finding(program: str, rule: str, message: str) -> Finding:
    return Finding(f"<audit:{program}>", 0, 0, rule, message)


# -- jaxpr / HLO counters ---------------------------------------------------

# primitives that cross the device/host boundary inside a program
_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "infeed", "outfeed"}

_HLO_COLLECTIVE_RE = re.compile(
    r"=\s+\S+\s+(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\(")


def jaxpr_prim_counts(jaxpr, counts: Optional[Dict[str, int]] = None):
    """Primitive histogram of a jaxpr, recursing into every sub-jaxpr
    (scan/while/cond bodies, custom_vjp branches, pjit calls)."""
    counts = counts if counts is not None else {}
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            _sub_counts(v, counts)
    return counts


def _sub_counts(v, counts):
    if hasattr(v, "eqns"):                      # open Jaxpr
        jaxpr_prim_counts(v, counts)
    elif hasattr(v, "jaxpr"):                   # ClosedJaxpr
        jaxpr_prim_counts(v.jaxpr, counts)
    elif isinstance(v, (list, tuple)):
        for e in v:
            _sub_counts(e, counts)


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Collective-op histogram of a compiled module's HLO text.  Async
    pairs count once (the ``-start`` carries the op; ``-done`` has no
    parenthesized operand list in the matched position)."""
    counts: Dict[str, int] = {}
    for op in _HLO_COLLECTIVE_RE.findall(hlo_text):
        counts[op] = counts.get(op, 0) + 1
    return counts


def _check_budget(findings, program: str, counts: Dict[str, int],
                  budget: Dict[str, Tuple[int, Optional[int]]],
                  why: str) -> None:
    for op, (lo, hi) in budget.items():
        n = counts.get(op, 0)
        if hi is not None and n > hi:
            findings.append(_finding(program, "A201", (
                f"collective-budget-exceeded: {program} compiles to {n} "
                f"{op} op(s), budget {hi} — {why}")))
        elif n < lo:
            findings.append(_finding(program, "A201", (
                f"collective-budget-exceeded: {program} compiles to {n} "
                f"{op} op(s), expected at least {lo} — {why}")))


def _check_transfers(findings, program: str,
                     counts: Dict[str, int]) -> None:
    hits = {p: n for p, n in counts.items() if p in _CALLBACK_PRIMS}
    for prim, n in sorted(hits.items()):
        findings.append(_finding(program, "A202", (
            f"host-transfer-in-program: {program} traces {n} '{prim}' "
            f"primitive(s) — every dispatch pays a device->host round trip "
            f"(30-100 ms tunneled); land results with the program's "
            f"returns, never a callback")))


# -- axis helpers -----------------------------------------------------------

def _axes_of(entry) -> set:
    if entry is None:
        return set()
    if isinstance(entry, (tuple, list)):
        return set(entry)
    return {entry}


def _pad_spec(spec, rank: int) -> list:
    entries = list(tuple(spec)) if spec is not None else []
    return entries + [None] * (rank - len(entries))


# -- leg 1: shardcheck ------------------------------------------------------

def _leg_shardcheck(findings, report, mesh, seed: Optional[str]) -> None:
    from jax.sharding import PartitionSpec as P
    from ..parallel import fsdp
    from ..serving import sharded

    serving_layout = sharded.ServingLayout()
    if seed == "spec_axis":
        serving_layout = sharded.ServingLayout(tp_axis="model")
    elif seed == "row_parallel":
        class _RowParallelSeed(sharded.ServingLayout):
            def attn_out(self):
                return P(None, self.tp_axis)
        serving_layout = _RowParallelSeed()

    mesh_axes = {str(a) for a in mesh.axis_names}
    checked = 0
    for label, layout in (("SpecLayout", fsdp.SpecLayout()),
                          ("ServingLayout", serving_layout)):
        for role, shape, spec in fsdp.audit_spec_table(layout):
            checked += 1
            entries = _pad_spec(spec, len(shape))
            for d, entry in enumerate(entries):
                for ax in sorted(_axes_of(entry)):
                    if ax not in mesh_axes:
                        findings.append(_finding("shardcheck", "A101", (
                            f"spec-axis-missing: {label}.{role} dim {d} "
                            f"names mesh axis '{ax}' but the mesh only has "
                            f"{sorted(mesh_axes)} — the spec can never "
                            f"apply; every leaf silently replicates")))
                        continue
                axes = [a for a in _axes_of(entry) if a in mesh_axes]
                if not axes:
                    continue
                degree = 1
                for ax in axes:
                    degree *= int(mesh.shape[ax])
                if shape[d] % degree != 0:
                    findings.append(_finding("shardcheck", "A102", (
                        f"spec-dim-indivisible: {label}.{role} shards dim "
                        f"{d} (size {shape[d]}) over {tuple(axes)} (degree "
                        f"{degree}) but {shape[d]} % {degree} != 0 — "
                        f"filter_spec degrades this leaf to replicated on "
                        f"the canonical geometry, a silent 1/{degree} "
                        f"memory and bandwidth loss")))

        # A104: the bit-exactness precondition only binds serving layouts
        if isinstance(layout, sharded.ServingLayout):
            for entry_name, spec in sharded.audit_layout_invariants(layout):
                findings.append(_finding("shardcheck", "A104", (
                    f"row-parallel-not-replicated: {label}.{entry_name}() "
                    f"is {spec}, must be P() — sharding a row-parallel "
                    f"contraction dim turns the matmul into per-device "
                    f"partial sums + psum, reordering the float reduction "
                    f"and breaking greedy token parity with solo generate "
                    f"(PR 19)")))

    # A103: compose_spec may only insert the fsdp axis on dim 0
    compose = fsdp.compose_spec
    if seed == "contraction_shard":
        ax, n = fsdp.fsdp_axis_name(mesh), fsdp.fsdp_size(mesh)

        def compose(shape, base, mesh_):
            if len(shape) >= 2 and shape[1] % n == 0:
                entries = _pad_spec(base, len(shape))
                if entries[1] is None:
                    entries[1] = ax
                    return P(*entries)
            return fsdp.compose_spec(shape, base, mesh_)

    for role, shape, base in fsdp.audit_spec_table(fsdp.SpecLayout()):
        if len(shape) < 2 or role == "kv_cache":
            continue
        composed = compose(shape, base, mesh)
        if composed is None:
            continue
        base_entries = _pad_spec(base, len(shape))
        comp_entries = _pad_spec(composed, len(shape))
        for d in range(1, len(shape)):
            added = _axes_of(comp_entries[d]) - _axes_of(base_entries[d])
            if added:
                findings.append(_finding("shardcheck", "A103", (
                    f"contraction-dim-shard: composing {role} {shape} adds "
                    f"axis {sorted(added)} on dim {d} — only dim 0 (the "
                    f"output dim) may take the fsdp axis; sharding a "
                    f"contraction dim makes XLA compute partial sums + "
                    f"psum, changing the reduction order that stages 1/2 "
                    f"bit-parity depends on (PR 8)")))
    report["legs"].append({"leg": "shardcheck", "rows": checked})


# -- leg 2: serving programs (trace + sharded compile) ----------------------

def _audit_model():
    import numpy as np
    import mxtpu as mx
    from .. import autograd
    from ..gluon.model_zoo.transformer import transformer_lm
    from ..ndarray.ndarray import NDArray
    mx.rng.seed(0)
    model = transformer_lm("tiny", vocab_size=_VOCAB)
    model.initialize()
    # one (1, 1) forward completes the deferred shapes (the engine's
    # _materialize_params does the same before its first dispatch)
    with autograd.predict_mode():
        model(NDArray(np.zeros((1, 1), np.int32)))
    return model


def _leg_serving(findings, report, mesh, seed: Optional[str]) -> None:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..serving import kv, sharded

    model = _audit_model()
    programs = kv.audit_programs(model, _SLOTS, _TOT, _CHUNK, _K)

    for name, fn, args in programs:
        traced_fn = fn
        if seed == "host_transfer" and name == "serving_decode":
            base = fn

            def traced_fn(*a):
                out = base(*a)
                jax.debug.callback(lambda x: None, out[3])
                return out

        jaxpr = jax.make_jaxpr(traced_fn)(*args)
        counts = jaxpr_prim_counts(jaxpr.jaxpr)
        _check_transfers(findings, name, counts)
        report["programs"][name] = {
            "eqns": sum(counts.values()),
            "callbacks": sum(counts.get(p, 0) for p in _CALLBACK_PRIMS),
        }

    # sharded fsdp×tp decode: compile on the virtual mesh — under
    # layout_scope, exactly as the engine's dispatch traces — and hold the
    # compiled module to its collective budget.  The canonical geometry
    # compiles with exactly TWO all-reduces, both order-exact integer/max
    # reductions (the one-hot embedding lookup over the vocab-sharded
    # table sums exact zeros; the greedy argmax over vocab shards is an
    # associative max).  Any all-reduce beyond those is a float-dot
    # partial-sum psum — a sharded row-parallel contraction — which
    # reorders the reduction and breaks greedy token parity with solo
    # generate (PR 19).
    from ..parallel import fsdp
    layout = sharded.ServingLayout()
    if seed == "extra_collective":
        class _RowParallelSeed(sharded.ServingLayout):
            def attn_out(self):
                return P(None, self.tp_axis)
        layout = _RowParallelSeed()

    # a FRESH decode builder: jax.jit caches its traced jaxpr by avals, so
    # the instance make_jaxpr traced above would hand the scoped lower its
    # unscoped trace (no activation constraints) and the budget would
    # measure the wrong program
    fn = kv.build_decode(model, _SLOTS, _TOT, _CHUNK)
    args = programs[0][2]
    repl = NamedSharding(mesh, P())
    placed = (sharded.place_params(args[0], mesh, layout),
              sharded.place_cache(args[1], mesh, layout),
              *(jax.device_put(a, repl) for a in args[2:]))
    with fsdp.layout_scope(layout, mesh):
        hlo = fn.lower(*placed).compile().as_text()
    counts = hlo_collective_counts(hlo)
    prog = f"serving_decode[fsdp={mesh.shape['fsdp']},tp={mesh.shape['tp']}]"
    _check_budget(findings, prog, counts,
                  {"all-reduce": (0, 2), "all-to-all": (0, 0)},
                  "the canonical sharded decode's only all-reduces are the "
                  "two exact reductions (one-hot embedding lookup, vocab "
                  "argmax); a count beyond 2 means a float contraction got "
                  "sharded and greedy token parity with solo generate is "
                  "gone (PR 19)")
    report["programs"][prog] = {"collectives": counts}
    report["legs"].append(
        {"leg": "serving",
         "programs": [name for name, _fn, _args in programs] + [prog]})


# -- leg 3: ZeRO bucketed update --------------------------------------------

def _leg_zero(findings, report, seed: Optional[str]) -> None:
    import jax
    import jax.numpy as jnp
    from ..parallel import zero as zero_mod
    from ..parallel.mesh import make_mesh
    from .. import optimizer as opt_mod

    mesh = make_mesh((_MIN_DEVICES,), ("dp",))
    opt = opt_mod.create("sgd", learning_rate=0.05, momentum=0.9)
    params = [jnp.ones((64, 8), jnp.float32),
              jnp.zeros((128,), jnp.float32),
              jnp.ones((16,), jnp.float32)]
    n = len(params)
    layout = zero_mod.ZeroLayout(params, [1.0] * n, [1.0] * n,
                                 _MIN_DEVICES)
    states, residuals = zero_mod.init_zero_states(opt, layout, params, mesh)
    zero_update = zero_mod.build_zero_update(opt, layout, mesh)
    grads = [jnp.full_like(p, 0.5) for p in params]
    scalars = (jnp.float32(0.05), jnp.float32(0.0), jnp.float32(1.0),
               jnp.float32(0.0), jnp.int32(1))
    hlo = jax.jit(zero_update).lower(
        params, grads, states, residuals, *scalars).compile().as_text()
    counts = hlo_collective_counts(hlo)
    prog = f"zero_update[dp={_MIN_DEVICES}]"
    _check_budget(findings, prog, counts,
                  {"all-reduce": (0, 0), "all-gather": (1, None)},
                  "the ZeRO update is reduce-scatter -> shard-update -> "
                  "all-gather by construction; an all-reduce means the "
                  "update fell back to replicated math (the pre-PR-4 "
                  "monolithic step) and the 1/N state residency is fiction")
    report["programs"][prog] = {"collectives": counts}
    report["legs"].append({"leg": "zero", "programs": [prog]})


# -- leg 4: fused training step ---------------------------------------------

def _leg_fused_step(findings, report, seed: Optional[str]) -> None:
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import nd
    from mxtpu.gluon import nn
    from mxtpu.gluon.block import HybridBlock
    from mxtpu.io import DataBatch, DataDesc

    class _AuditNet(HybridBlock):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Dense(16, in_units=12)
            self.fc2 = nn.Dense(10, in_units=16)

        def forward(self, x):
            return self.fc2(self.fc1(x).relu())

    mx.rng.seed(0)
    mod = mx.Module(_AuditNet(), data_names=("data",),
                    label_names=("softmax_label",))
    mod.bind(data_shapes=[DataDesc("data", (8, 12))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    rs = np.random.RandomState(0)
    batch = DataBatch(data=[nd.array(rs.rand(8, 12).astype(np.float32))],
                      label=[nd.array(rs.randint(0, 10, 8)
                                      .astype(np.float32))])
    mod.forward_backward(batch)
    mod.update()
    jitted, avals = mod._step_exec.audit_entry()
    jaxpr = jax.make_jaxpr(jitted)(*avals)
    counts = jaxpr_prim_counts(jaxpr.jaxpr)
    _check_transfers(findings, "module_step", counts)
    report["programs"]["module_step"] = {
        "eqns": sum(counts.values()),
        "callbacks": sum(counts.get(p, 0) for p in _CALLBACK_PRIMS),
    }
    report["legs"].append({"leg": "fused_step", "programs": ["module_step"]})


# -- leg 5: retrace-closure proof -------------------------------------------

def _leg_keys(findings, report, seed: Optional[str]) -> None:
    from ..serving import engine as engine_mod

    bucket = (lambda n: n) if seed == "open_keys" else None
    specs = engine_mod.audit_key_specs(_MAX_LEN, _SLOTS, _CHUNK,
                                       _PREFILL_CHUNK, _K, bucket=bucket)
    # the admissible request domain: every prompt length x a spread of
    # generation lengths, totals clamped to the model window
    domain = [(plen, min(plen + new, _MAX_LEN))
              for plen in range(1, _MAX_LEN + 1)
              for new in (1, 7, 33)]
    audited = {}
    for name, keys_of, bounds in specs:
        keys = set()
        comp_vals = [set() for _ in bounds]
        for plen, total in domain:
            for key in keys_of(plen, total):
                keys.add(key)
                for i, c in enumerate(key):
                    comp_vals[i].add(c)
        audited[name] = {"distinct_keys": len(keys),
                         "bound": 1}
        for b in bounds:
            audited[name]["bound"] *= b
        for i, (vals, bound) in enumerate(zip(comp_vals, bounds)):
            if len(vals) > bound:
                findings.append(_finding(name, "A301", (
                    f"open-program-key-set: {name} key component {i} takes "
                    f"{len(vals)} distinct values over the admissible "
                    f"request domain, bound {bound} — an unbucketed "
                    f"quantity leaked into the program key; every new "
                    f"value mints a full recompile (the trace-once "
                    f"contract requires bucket32 at the key site)")))
    report["legs"].append({"leg": "keys", "programs": audited})


# -- driver -----------------------------------------------------------------

def run_audit(seed: Optional[str] = None,
              legs: Optional[Sequence[str]] = None):
    """Run the audit legs (all by default), optionally with one seeded
    violation.  Returns ``(findings, report)``."""
    from ..parallel.mesh import make_mesh

    active = tuple(legs) if legs else _LEGS
    findings: List[Finding] = []
    report = {"programs": {}, "legs": []}
    mesh = None
    if "shardcheck" in active or "serving" in active:
        mesh = make_mesh((4, 2), ("fsdp", "tp"))
    if "shardcheck" in active:
        _leg_shardcheck(findings, report, mesh, seed)
    if "serving" in active:
        _leg_serving(findings, report, mesh, seed)
    if "zero" in active:
        _leg_zero(findings, report, seed)
    if "fused_step" in active:
        _leg_fused_step(findings, report, seed)
    if "keys" in active:
        _leg_keys(findings, report, seed)
    return findings, report


def _filter(findings: List[Finding], select, ignore) -> List[Finding]:
    if select:
        findings = [f for f in findings if f.rule in set(select)]
    if ignore:
        findings = [f for f in findings if f.rule not in set(ignore)]
    return findings


def _respawn(expect_fail: bool, fmt: str, select, ignore) -> int:
    """Child re-exec with enough virtual CPU devices.  The audit needs the
    8-device mesh; a bare CLI invocation starts with 1 CPU device and the
    backend cannot be re-initialized in-process, so re-run ourselves with
    the forced device count (same shape the tier-1 guards use)."""
    import subprocess
    argv = [sys.executable, "-m", "mxtpu.analysis", "--audit"]
    if expect_fail:
        argv.append("--expect-fail")
    if fmt != "text":
        argv += ["--format", fmt]
    for r in select or ():
        argv += ["--select", r]
    for r in ignore or ():
        argv += ["--ignore", r]
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (flags +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["MXTPU_AUDIT_CHILD"] = "1"
    return subprocess.run(argv, env=env).returncode


def main_audit(expect_fail: bool = False, fmt: str = "text",
               select=None, ignore=None) -> int:
    import jax
    if len(jax.devices()) < _MIN_DEVICES:
        if os.environ.get("MXTPU_AUDIT_CHILD") == "1":
            print(f"audit: needs >= {_MIN_DEVICES} devices, have "
                  f"{len(jax.devices())} even after re-exec", file=sys.stderr)
            return 2
        return _respawn(expect_fail, fmt, select, ignore)

    if expect_fail:
        return _main_expect_fail(select, ignore)

    findings, report = run_audit()
    findings = _filter(findings, select, ignore)
    if fmt == "json":
        import json
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps(
            {"version": 2, "audit": True,
             "findings": [{"path": f.path, "line": f.line, "col": f.col,
                           "rule": f.rule, "message": f.message}
                          for f in findings],
             "counts": counts, "report": report},
            indent=1, sort_keys=True, default=str))
    else:
        for f in findings:
            print(f.format())
        for prog, info in sorted(report["programs"].items()):
            print(f"audit: {prog}: {info}")
        print(f"audit: {len(report['programs'])} program(s), "
              f"{len(findings)} finding(s)")
    return 1 if findings else 0


def _main_expect_fail(select, ignore) -> int:
    """Prove detection: each seeded violation must surface its rule."""
    missed = []
    for seed, rule, legs in _SEEDS:
        findings, _ = run_audit(seed=seed, legs=legs)
        findings = _filter(findings, select, ignore)
        hits = [f for f in findings if f.rule == rule]
        status = "DETECTED" if hits else "MISSED"
        print(f"audit --expect-fail: seed '{seed}' -> {rule}: {status} "
              f"({len(hits)} finding(s))")
        if not hits:
            missed.append((seed, rule))
    if missed:
        print(f"audit --expect-fail: {len(missed)} seeded violation(s) "
              f"NOT detected: {missed}", file=sys.stderr)
        return 1
    print(f"audit --expect-fail: all {len(_SEEDS)} seeded violations "
          f"detected")
    return 0
