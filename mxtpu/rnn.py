"""``mx.rnn`` legacy namespace — bucketing data iterator + cell re-exports.

Reference: ``python/mxnet/rnn/`` (legacy RNN cells shared with gluon, plus
``BucketSentenceIter`` in rnn/io.py — the variable-length batching front end
that feeds ``BucketingModule``). On TPU, bucketing is also the recompilation
policy: one XLA program per bucket shape, cached by the CachedOp signature
(docs/faq/bucketing.md capability, SURVEY §5 long-context requirement).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from . import ndarray as nd
from .gluon.rnn.rnn_cell import (BidirectionalCell, DropoutCell, GRUCell,
                                 LSTMCell, ModifierCell, RecurrentCell,
                                 ResidualCell, RNNCell, SequentialRNNCell,
                                 ZoneoutCell)
from .io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ModifierCell", "ResidualCell", "ZoneoutCell", "RecurrentCell"]


class BucketSentenceIter(DataIter):
    """Bucketed iterator over tokenized sentences (rnn/io.py:BucketSentenceIter).

    Sentences (lists of int ids) are assigned to the smallest bucket that fits,
    padded with ``invalid_label``; each batch comes from ONE bucket and carries
    its ``bucket_key`` so ``BucketingModule`` selects the matching compiled
    program. Labels are the next-token shift of the data; pad positions hold
    ``invalid_label``, which the loss must mask — pair with
    ``SoftmaxCrossEntropyLoss(ignore_label=invalid_label)`` (the gluon-side
    equivalent of the reference's ``SoftmaxOutput(use_ignore=True)``).
    """

    def __init__(self, sentences: Sequence[Sequence[int]], batch_size: int,
                 buckets: Optional[List[int]] = None, invalid_label: int = -1,
                 data_name: str = "data", label_name: str = "softmax_label",
                 dtype: str = "float32", layout: str = "NT", shuffle: bool = False):
        super().__init__(batch_size)
        if buckets is None:
            # reference default (rnn/io.py): keep only lengths with at least
            # batch_size sentences as bucket boundaries — rarer lengths are
            # absorbed into the next larger bucket instead of yielding zero
            # batches; the max length is always a boundary so nothing long is
            # silently dropped
            counts: dict = {}
            for s in sentences:
                if len(s) >= 2:
                    counts[len(s)] = counts.get(len(s), 0) + 1
            buckets = sorted(l for l, c in counts.items() if c >= batch_size)
            if counts and (not buckets or buckets[-1] < max(counts)):
                buckets.append(max(counts))
        self.buckets = sorted(buckets)
        if not self.buckets:
            raise ValueError(
                "BucketSentenceIter: no usable buckets — every sentence is "
                "shorter than 2 tokens or the bucket list is empty")
        self.data_name, self.label_name = data_name, label_name
        self.invalid_label = invalid_label
        self.dtype = dtype
        if layout != "NT":
            raise ValueError("layout NT (batch, time) is the supported layout")
        self._shuffle = shuffle

        self.data: List[List[np.ndarray]] = [[] for _ in self.buckets]
        ndiscard = 0
        for s in sentences:
            if len(s) < 2:
                ndiscard += 1
                continue
            bkt = next((i for i, b in enumerate(self.buckets) if b >= len(s)),
                       None)
            if bkt is None:
                ndiscard += 1
                continue
            row = np.full(self.buckets[bkt], invalid_label, np.int64)
            row[:len(s)] = s
            self.data[bkt].append(row)
        self.ndiscard = ndiscard
        self.default_bucket_key = max(self.buckets)
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key),
                         self.dtype)]

    def reset(self):
        self._plan = []                       # (bucket_idx, start) per batch
        for i, rows in enumerate(self.data):
            if self._shuffle:
                # fresh permutation each epoch, like NDArrayIter's np.random use
                np.random.shuffle(rows)
            for start in range(0, len(rows) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, start))
        if self._shuffle:
            np.random.shuffle(self._plan)
        self._cursor = 0

    def next(self) -> DataBatch:
        if self._cursor >= len(self._plan):
            raise StopIteration
        bkt, start = self._plan[self._cursor]
        self._cursor += 1
        rows = np.stack(self.data[bkt][start:start + self.batch_size])
        # next-token labels; the pad slot after sentence end holds invalid_label
        labels = np.full_like(rows, self.invalid_label)
        labels[:, :-1] = rows[:, 1:]
        key = self.buckets[bkt]
        dt = np.dtype(self.dtype)
        return DataBatch(
            data=[nd.array(rows.astype(dt))],
            label=[nd.array(labels.astype(dt))],
            bucket_key=key,
            provide_data=[DataDesc(self.data_name, (self.batch_size, key),
                                   self.dtype)],
            provide_label=[DataDesc(self.label_name, (self.batch_size, key),
                                    self.dtype)])
