"""mx.image parity — host-side decode/resize/augmenters + ImageIter.

The reference's ``src/io/image_aug_default.cc`` + ``python/mxnet/image`` do OpenCV
augmentation on CPU worker threads; here PIL/numpy fill that role (DataLoader threads),
and anything per-batch on device goes through the image ops (``nd.image``-style).
"""

from .image import (CreateAugmenter, HorizontalFlipAug, CastAug, CenterCropAug,
                    ColorJitterAug, ForceResizeAug, ImageIter, RandomCropAug,
                    ResizeAug, color_normalize, fixed_crop, imdecode, imread,
                    imresize, random_crop, center_crop, resize_short)
from .detection import (CreateDetAugmenter, CreateMultiRandCropAugmenter,
                        DetAugmenter, DetBorrowAug, DetHorizontalFlipAug,
                        DetRandomCropAug, DetRandomPadAug, DetRandomSelectAug,
                        ImageDetIter)
