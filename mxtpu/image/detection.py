"""Detection image pipeline — capability parity with
``python/mxnet/image/detection.py`` (DetAugmenter family, CreateDetAugmenter,
ImageDetIter) and ``src/io/image_det_aug_default.cc``.

Labels are (num_object, 5+) rows ``[cls_id, xmin, ymin, xmax, ymax, ...]`` with
coordinates normalized to [0, 1]; augmenters transform image and label
together. The iterator emits fixed-shape label batches padded with -1 rows
(the convention ``contrib.MultiBoxTarget`` consumes).
"""

from __future__ import annotations

import random as pyrandom
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ndarray.ndarray import NDArray
from .image import (Augmenter, CastAug, ColorJitterAug, ForceResizeAug,
                    HorizontalFlipAug, ImageIter, ResizeAug, fixed_crop,
                    imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


def _as_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)


class DetAugmenter:
    """Base detection augmenter: ``__call__(src, label) -> (src, label)``."""

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection chain
    (detection.py:65)."""

    def __init__(self, augmenter: Augmenter):
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply one of ``aug_list`` (or none, with ``skip_prob``)
    (detection.py:90)."""

    def __init__(self, aug_list: Sequence[DetAugmenter], skip_prob: float = 0.0):
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and x-coords together with probability p (detection.py:126)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            arr = _as_np(src)[:, ::-1]
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
            return NDArray(np.ascontiguousarray(arr)), label
        return src, label


def _crop_label(label, x0, y0, w, h, im_w, im_h, min_eject_coverage):
    """Re-express labels inside a pixel crop; eject low-coverage objects."""
    out = label.copy()
    # to pixels
    px = out[:, (1, 3)] * im_w
    py = out[:, (2, 4)] * im_h
    areas = np.maximum(0, px[:, 1] - px[:, 0]) * np.maximum(0, py[:, 1] - py[:, 0])
    nx = np.clip(px - x0, 0, w)
    ny = np.clip(py - y0, 0, h)
    new_areas = np.maximum(0, nx[:, 1] - nx[:, 0]) * \
        np.maximum(0, ny[:, 1] - ny[:, 0])
    coverage = new_areas / np.maximum(areas, 1e-12)
    keep = coverage >= min_eject_coverage
    out[:, (1, 3)] = nx / w
    out[:, (2, 4)] = ny / h
    return out[keep]


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (detection.py:152): sampled aspect/area with an
    object-coverage constraint; labels re-normalized, marginal objects
    ejected."""

    def __init__(self, min_object_covered: float = 0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage: float = 0.3, max_attempts: int = 50):
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = _as_np(src)
        im_h, im_w = arr.shape[0], arr.shape[1]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range) * im_h * im_w
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            w = int(round(np.sqrt(area * ratio)))
            h = int(round(np.sqrt(area / ratio)))
            if w > im_w or h > im_h or w < 1 or h < 1:
                continue
            x0 = pyrandom.randint(0, im_w - w)
            y0 = pyrandom.randint(0, im_h - h)
            # coverage of each gt by the crop
            px = label[:, (1, 3)] * im_w
            py = label[:, (2, 4)] * im_h
            areas = np.maximum(0, px[:, 1] - px[:, 0]) * \
                np.maximum(0, py[:, 1] - py[:, 0])
            ix = np.clip(px, x0, x0 + w)
            iy = np.clip(py, y0, y0 + h)
            inter = np.maximum(0, ix[:, 1] - ix[:, 0]) * \
                np.maximum(0, iy[:, 1] - iy[:, 0])
            cov = inter / np.maximum(areas, 1e-12)
            if label.shape[0] and cov.max() < self.min_object_covered:
                continue
            new_label = _crop_label(label, x0, y0, w, h, im_w, im_h,
                                    self.min_eject_coverage)
            if label.shape[0] and new_label.shape[0] == 0:
                continue
            cropped = NDArray(np.ascontiguousarray(arr[y0:y0 + h, x0:x0 + w]))
            return cropped, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad (detection.py:324): place the image on a larger
    canvas filled with ``pad_val``; labels shrink accordingly."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts: int = 50, pad_val=(127, 127, 127)):
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _as_np(src)
        im_h, im_w = arr.shape[0], arr.shape[1]
        for _ in range(self.max_attempts):
            scale = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = scale * im_h * im_w
            w = int(round(np.sqrt(area * ratio)))
            h = int(round(np.sqrt(area / ratio)))
            if w < im_w or h < im_h:
                continue
            x0 = pyrandom.randint(0, w - im_w)
            y0 = pyrandom.randint(0, h - im_h)
            canvas = np.empty((h, w, arr.shape[2]), arr.dtype)
            canvas[...] = np.asarray(self.pad_val, arr.dtype)
            canvas[y0:y0 + im_h, x0:x0 + im_w] = arr
            new_label = label.copy()
            new_label[:, (1, 3)] = (label[:, (1, 3)] * im_w + x0) / w
            new_label[:, (2, 4)] = (label[:, (2, 4)] * im_h + y0) / h
            return NDArray(canvas), new_label
        return src, label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """detection.py:418: a DetRandomSelectAug over per-constraint croppers.
    Scalar args broadcast; list args must share length."""
    mocs = min_object_covered if isinstance(min_object_covered, list) \
        else [min_object_covered]
    arrs = aspect_ratio_range if isinstance(aspect_ratio_range, list) \
        else [aspect_ratio_range]
    ars = area_range if isinstance(area_range, list) else [area_range]
    mecs = min_eject_coverage if isinstance(min_eject_coverage, list) \
        else [min_eject_coverage]
    n = max(len(mocs), len(arrs), len(ars), len(mecs))

    def pick(lst, i):
        return lst[i] if len(lst) > 1 else lst[0]

    augs = [DetRandomCropAug(pick(mocs, i), pick(arrs, i), pick(ars, i),
                             pick(mecs, i), max_attempts) for i in range(n)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)) -> List[DetAugmenter]:
    """detection.py:483 parity: the standard SSD augmentation chain."""
    if rand_gray or pca_noise or hue:
        raise NotImplementedError(
            "rand_gray/pca_noise/hue augmenters are not implemented yet; "
            "drop the argument or add the augmenter to aug_list explicitly")
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])), min_eject_coverage,
            max_attempts, skip_prob=1.0 - rand_crop)
        auglist.append(crop)
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range, (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], skip_prob=1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # final force-resize to the network input
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        from .image import color_normalize

        class _Norm(Augmenter):
            def __call__(self, img):
                return color_normalize(
                    img, np.asarray(mean if mean is not None else 0.0,
                                    np.float32),
                    None if std is None else np.asarray(std, np.float32))

        auglist.append(DetBorrowAug(_Norm()))
    return auglist


class ImageDetIter(ImageIter):
    """Detection batch iterator (detection.py:625): emits NCHW data plus
    fixed-shape (batch, max_objects, label_width) labels padded with -1."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False, aug_list=None,
                 imglist=None, label_shape: Optional[Tuple[int, int]] = None,
                 **kwargs):
        det_kwargs = {k: kwargs.pop(k) for k in list(kwargs) if k in (
            "resize", "rand_crop", "rand_pad", "rand_gray", "rand_mirror",
            "mean", "std", "brightness", "contrast", "saturation", "pca_noise",
            "hue", "inter_method", "min_object_covered", "aspect_ratio_range",
            "area_range", "min_eject_coverage", "max_attempts", "pad_val")}
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle, aug_list=[],
                         imglist=imglist, **kwargs)
        self.auglist = []  # image-only chain unused; det chain below
        self.det_auglist = (CreateDetAugmenter(tuple(data_shape), **det_kwargs)
                            if aug_list is None else list(aug_list))
        self.label_shape = label_shape or self._estimate_label_shape()

    @staticmethod
    def _parse_label(label) -> np.ndarray:
        """detection.py:712 raw label layout: [header_w, obj_w, <header...>,
        obj0..objN] → (N, obj_w) float array; plain (N,5+) arrays pass
        through."""
        raw = np.asarray(label, np.float32).ravel()
        arr2d = np.asarray(label, np.float32)
        if arr2d.ndim == 2 and arr2d.shape[1] >= 5:
            return arr2d
        header_w = int(raw[0])
        obj_w = int(raw[1])
        if header_w < 2 or obj_w < 5:
            raise RuntimeError(f"invalid det label header {raw[:2]}")
        body = raw[header_w:]
        n = body.size // obj_w
        return body[:n * obj_w].reshape(n, obj_w)

    def _estimate_label_shape(self) -> Tuple[int, int]:
        max_n, width = 1, 5
        for idx in self._items:
            lab = self._parse_label(self._read_label(idx))
            max_n = max(max_n, lab.shape[0])
            width = max(width, lab.shape[1])
        return (max_n, width)

    def _read(self, idx):
        img, raw_label = self._read_raw(idx)
        label = self._parse_label(raw_label)
        for aug in self.det_auglist:
            img, label = aug(img, label)
        out = np.full(self.label_shape, -1.0, np.float32)
        n = min(label.shape[0], self.label_shape[0])
        if n:
            out[:n, :label.shape[1]] = label[:n, :self.label_shape[1]]
        return img, out

    def reshape(self, data_shape=None, label_shape=None):
        if label_shape is not None:
            self.label_shape = tuple(label_shape)
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
            # swap only the final force-resize target; the configured chain
            # (crop/pad/mirror/normalize) stays intact
            for aug in self.det_auglist:
                if isinstance(aug, DetBorrowAug) and \
                        isinstance(aug.augmenter, ForceResizeAug):
                    aug.augmenter = ForceResizeAug(
                        (self.data_shape[2], self.data_shape[1]))
        return self
