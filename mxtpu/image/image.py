"""Image decode/augment — parity with ``python/mxnet/image/image.py`` essentials."""

from __future__ import annotations

import io
import os
import random as pyrandom
from typing import List, Optional, Tuple

import numpy as np

from .. import ndarray as nd
from ..ndarray.ndarray import NDArray


def _pil():
    from PIL import Image
    return Image


def imdecode(buf: bytes, flag: int = 1, to_rgb: bool = True) -> NDArray:
    """Decode compressed image bytes → HWC uint8 NDArray (image.py imdecode).

    JPEGs take the native libjpeg path (mxtpu_io.cc — the reference's decode
    hot loop, iter_image_recordio_2.cc:138-149; the C call releases the GIL so
    iterator thread pools scale across cores); PIL handles everything else."""
    if flag == 1 and buf[:2] == b"\xff\xd8":
        from .. import native
        arr = native.jpeg_decode(bytes(buf))
        if arr is not None:
            return nd.array(arr, dtype="uint8")
    img = _pil().open(io.BytesIO(buf))
    if flag == 0:
        img = img.convert("L")
        arr = np.asarray(img)[:, :, None]
    else:
        img = img.convert("RGB")
        arr = np.asarray(img)
    return nd.array(arr.astype(np.uint8), dtype="uint8")


def imread(filename: str, flag: int = 1, to_rgb: bool = True) -> NDArray:
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = _pil().fromarray(arr.squeeze(-1) if squeeze else arr.astype(np.uint8))
    out = np.asarray(pil.resize((w, h), resample=_pil().BILINEAR))
    if squeeze:
        out = out[:, :, None]
    return nd.array(out.astype(arr.dtype), dtype=str(arr.dtype))


def resize_short(src, size: int, interp: int = 2) -> NDArray:
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, size * h // w
    else:
        new_w, new_h = size * w // h, size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0: int, y0: int, w: int, h: int, size=None, interp: int = 2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(nd.array(out, dtype=str(out.dtype)), size[0], size[1], interp)
    return nd.array(out, dtype=str(out.dtype))


def random_crop(src, size: Tuple[int, int], interp: int = 2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    cw, ch = size
    cw, ch = min(cw, w), min(ch, h)
    x0 = pyrandom.randint(0, w - cw)
    y0 = pyrandom.randint(0, h - ch)
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def center_crop(src, size: Tuple[int, int], interp: int = 2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    cw, ch = size
    x0 = max(0, (w - cw) // 2)
    y0 = max(0, (h - ch) // 2)
    return fixed_crop(src, x0, y0, min(cw, w), min(ch, h), size, interp), (x0, y0, cw, ch)


def color_normalize(src: NDArray, mean, std=None) -> NDArray:
    out = src.astype("float32") - (mean if isinstance(mean, NDArray) else nd.array(mean))
    if std is not None:
        out = out / (std if isinstance(std, NDArray) else nd.array(std))
    return out


# ---------------------------------------------------------------------------
# augmenters (image.py Augmenter chain parity)
# ---------------------------------------------------------------------------


class Augmenter:
    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size: int, interp: int = 2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size: Tuple[int, int], interp: int = 2):
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src.asnumpy()[:, ::-1]
            return nd.array(np.ascontiguousarray(arr), dtype=str(arr.dtype))
        return src


class CastAug(Augmenter):
    def __init__(self, typ: str = "float32"):
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness: float = 0, contrast: float = 0,
                 saturation: float = 0):
        self.b, self.c, self.s = brightness, contrast, saturation

    def __call__(self, src):
        arr = src.asnumpy().astype(np.float32)
        if self.b:
            arr = arr * (1 + pyrandom.uniform(-self.b, self.b))
        if self.c:
            gray = arr.mean()
            arr = gray + (arr - gray) * (1 + pyrandom.uniform(-self.c, self.c))
        if self.s:
            g = arr.mean(axis=-1, keepdims=True)
            arr = g + (arr - g) * (1 + pyrandom.uniform(-self.s, self.s))
        return nd.array(np.clip(arr, 0, 255))


def CreateAugmenter(data_shape, resize: int = 0, rand_crop: bool = False,
                    rand_resize: bool = False, rand_mirror: bool = False,
                    mean=None, std=None, brightness: float = 0, contrast: float = 0,
                    saturation: float = 0, pca_noise: float = 0, inter_method: int = 2
                    ) -> List[Augmenter]:
    """image.py CreateAugmenter parity: build the standard augmentation chain."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if mean is not None or std is not None:
        class _Norm(Augmenter):
            def __call__(self, src):
                return color_normalize(src, nd.array(mean) if mean is not None else 0,
                                       nd.array(std) if std is not None else None)
        auglist.append(_Norm())
    return auglist


class ImageIter:
    """mx.image.ImageIter parity: .rec/.lst/folder-driven batch iterator with
    augmentation chain, NCHW output."""

    def __init__(self, batch_size: int, data_shape, label_width: int = 1,
                 path_imgrec: Optional[str] = None, path_imglist: Optional[str] = None,
                 path_root: str = "", shuffle: bool = False, aug_list=None,
                 imglist=None, preprocess_threads: int = 4, **kwargs):
        from ..io import DataBatch, DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        # CreateAugmenter takes the (C, H, W) sample shape, NOT batch-prefixed
        # (crop_size reads indices [2], [1] as (W, H) — image.py:1248 parity)
        self._fused_norm = None
        if aug_list is None:
            mean, std = kwargs.get("mean"), kwargs.get("std")
            from .. import native
            if (mean is not None or std is not None) and native.available():
                # native fast path: keep the aug chain on uint8 HWC and do the
                # cast+normalize+CHW transpose as ONE threaded C kernel over the
                # batch (iter_image_recordio_2.cc fused copy loop parity)
                self.auglist = [a for a in CreateAugmenter(
                    self.data_shape, **{k: v for k, v in kwargs.items()
                                        if k in ("resize", "rand_crop",
                                                 "rand_mirror")})
                    if not isinstance(a, CastAug)]
                self._fused_norm = (None if mean is None
                                    else np.asarray(mean, np.float32),
                                    None if std is None
                                    else np.asarray(std, np.float32))
            else:
                self.auglist = CreateAugmenter(
                    self.data_shape, **{k: v for k, v in kwargs.items()
                                        if k in ("resize", "rand_crop",
                                                 "rand_mirror", "mean", "std")})
        else:
            self.auglist = aug_list
        # decode/augment thread pool (OMP preprocess_threads parity — PIL decode
        # releases the GIL, so host decode parallelizes across the pool)
        self._pool = None
        if preprocess_threads and preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=preprocess_threads)
        self._out_dtype = kwargs.get("dtype", "float32")
        if self._out_dtype == "uint8":
            if kwargs.get("mean") is not None or kwargs.get("std") is not None:
                raise ValueError(
                    "dtype='uint8' emits raw pixels — normalization belongs "
                    "on-device for that layout; drop mean/std or use float32")
            # keep the data u8 end to end: no cast, no normalize in the chain
            self.auglist = [a for a in self.auglist
                            if not isinstance(a, CastAug)]
        self._items = []
        if path_imgrec:
            import threading
            from ..gluon.data import RecordFileDataset
            self._rec = RecordFileDataset(path_imgrec)
            self._rec_lock = threading.Lock()  # file reads serialize; decode doesn't
            self._items = list(range(len(self._rec)))
            self._mode = "rec"
            self._init_native_batch(path_imgrec)
        elif path_imglist:
            # .lst format (tools/im2rec.py): index \t label... \t rel_path
            entries = []
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 3:
                        continue
                    labels = np.asarray([float(x) for x in parts[1:-1]],
                                        np.float32)
                    entries.append([labels, parts[-1]])
            self._list = entries
            self._root = path_root
            self._items = list(range(len(entries)))
            self._mode = "list"
        elif imglist is not None:
            self._list = imglist
            self._root = path_root
            self._items = list(range(len(imglist)))
            self._mode = "list"
        else:
            raise ValueError("need path_imgrec, path_imglist, or imglist")
        self._shuffle = shuffle
        self.reset()

    def _init_native_batch(self, path_imgrec: str):
        """Whole-batch native path (iter_image_recordio_2.cc ParseChunk
        parity): when the aug chain reduces to crop+mirror[+normalize], one C
        call per batch does parallel record reads and the fused
        decode→crop→mirror→normalize→NCHW write into the batch slab — no
        per-record Python, no per-image array hops."""
        from .. import native
        self._nb = None
        if not native.available():
            return
        def reducible(a):
            # the C kernel hardcodes p=0.5 mirror and float32/uint8 output —
            # other parameters must take the per-image path
            if isinstance(a, HorizontalFlipAug):
                return a.p == 0.5
            if isinstance(a, CastAug):
                return a.typ == "float32"
            return isinstance(a, (RandomCropAug, CenterCropAug))

        if not all(reducible(a) for a in self.auglist):
            return
        mean, std = (self._fused_norm if self._fused_norm is not None
                     else (None, None))
        try:
            offsets, sizes = native.rio_index(path_imgrec)
        except Exception:
            return
        self._nb = {
            "path": path_imgrec, "offsets": offsets, "sizes": sizes,
            "mean": mean, "std": std,
            "rand_crop": any(isinstance(a, RandomCropAug) for a in self.auglist),
            "rand_mirror": any(isinstance(a, HorizontalFlipAug)
                               for a in self.auglist),
        }

    def _next_native(self, take, pad):
        """One C pass for the whole batch; None → caller falls back."""
        import struct as _struct

        from .. import native
        from ..io import DataBatch
        from ..recordio import _IR_FORMAT, _IR_SIZE
        nb = self._nb
        idx = np.asarray(take, np.int64)
        try:
            buf, rec_offs = native.rio_read_batch(
                nb["path"], nb["offsets"][idx], nb["sizes"][idx])
        except Exception:
            return None
        n = len(take)
        img_offs = np.empty(n, np.int64)
        img_sizes = np.empty(n, np.int64)
        labels = []
        for i in range(n):
            off = int(rec_offs[i])
            flag, label, _, _ = _struct.unpack_from(_IR_FORMAT, buf, off)
            hdr = _IR_SIZE + (4 * flag if flag > 0 else 0)
            if flag > 0:
                label = np.frombuffer(buf, np.float32, flag, off + _IR_SIZE)
            img_offs[i] = off + hdr
            img_sizes[i] = int(nb["sizes"][idx[i]]) - hdr
            labels.append(np.asarray(label, np.float32))
        data = native.decode_augment_batch(
            buf, img_offs, img_sizes,
            (self.data_shape[1], self.data_shape[2]),
            mean=nb["mean"], std=nb["std"], rand_crop=nb["rand_crop"],
            rand_mirror=nb["rand_mirror"],
            seed=pyrandom.getrandbits(63), out_dtype=self._out_dtype)
        if data is None:
            return None
        return DataBatch(data=[nd.array(data, dtype=self._out_dtype)],
                         label=[nd.array(np.stack(labels))], pad=pad)

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            pyrandom.shuffle(self._items)

    def _read_raw(self, idx):
        """Decode one sample WITHOUT augmentation: (img HWC, raw label)."""
        from .. import recordio
        if self._mode == "rec":
            with self._rec_lock:  # seek+read on the shared handle serializes
                raw = self._rec[idx]
            header, payload = recordio.unpack(raw)
            img = imdecode(payload)
            label = header.label
        else:
            label, path = self._list[idx][0], self._list[idx][-1]
            img = imread(os.path.join(self._root, path))
        return img, label

    def _read_label(self, idx):
        """Raw label only (no image decode) — used for label-shape scans."""
        from .. import recordio
        if self._mode == "rec":
            with self._rec_lock:
                raw = self._rec[idx]
            header, _ = recordio.unpack(raw)
            return header.label
        return self._list[idx][0]

    def _read(self, idx):
        img, label = self._read_raw(idx)
        for aug in self.auglist:
            img = aug(img)
        return img, np.asarray(label, np.float32)

    def __iter__(self):
        return self

    def __next__(self):
        from ..io import DataBatch
        if self._cursor >= len(self._items):
            raise StopIteration
        take = self._items[self._cursor:self._cursor + self.batch_size]
        pad = self.batch_size - len(take)
        take = take + [take[-1]] * pad
        if getattr(self, "_nb", None) is not None:
            batch = self._next_native(take, pad)
            if batch is not None:
                self._cursor += self.batch_size
                return batch
            self._nb = None            # e.g. non-JPEG records: stop retrying
        if self._pool is not None:
            results = list(self._pool.map(self._read, take))
        else:
            results = [self._read(i) for i in take]
        labels = [r[1] for r in results]
        arrs = [r[0].asnumpy() if isinstance(r[0], NDArray) else np.asarray(r[0])
                for r in results]
        self._cursor += self.batch_size
        if self._out_dtype == "uint8":
            data = np.stack([np.asarray(a).transpose(2, 0, 1)
                             for a in arrs]).astype(np.uint8)
            return DataBatch(data=[nd.array(data, dtype="uint8")],
                             label=[nd.array(np.stack(labels))], pad=pad)
        if self._fused_norm is not None and arrs[0].dtype == np.uint8:
            from .. import native
            data = native.nhwc_u8_to_nchw_f32(np.stack(arrs),
                                              self._fused_norm[0],
                                              self._fused_norm[1])
        else:
            data = np.stack([a.astype(np.float32).transpose(2, 0, 1)
                             for a in arrs])
        return DataBatch(data=[nd.array(data)],
                         label=[nd.array(np.stack(labels))], pad=pad)

    next = __next__
