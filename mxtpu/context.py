"""Device context abstraction for the TPU-native framework.

Capability parity with the reference's ``Context`` (``include/mxnet/base.h:133-203``:
``kCPU``/``kGPU``/``kCPUPinned``/``kCPUShared`` plus ``mx.context.Context`` stack in
``python/mxnet/context.py``), re-designed for TPU: a ``Context`` names a logical device
(``tpu(i)``, ``cpu(i)``) backed by a ``jax.Device``, and the module also exposes pod-slice
mesh helpers (``device_mesh``) that the reference has no equivalent of — on TPU the device
topology (ICI) is a first-class axis of the programming model rather than an opaque set of
GPU ordinals.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax

__all__ = [
    "Context",
    "cpu",
    "gpu",
    "tpu",
    "cpu_pinned",
    "current_context",
    "num_devices",
    "num_gpus",
    "num_tpus",
    "device_mesh",
]


class Context:
    """A logical device context.

    Unlike the reference (where Context is a (device-type, device-id) pair routing into
    per-device engine worker pools, ``src/engine/threaded_engine_perdevice.cc``), here a
    Context resolves to a ``jax.Device`` and placement is delegated to XLA: there is no
    user-visible stream or worker pool because XLA's async dispatch plays that role.

    ``Context`` is usable as a ``with``-target to set the thread-local default device,
    mirroring ``mx.Context.__enter__`` (python/mxnet/context.py).
    """

    # device type codes kept for serialization parity with the reference enum
    # (include/mxnet/base.h:139-146)
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 5}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devstr2type:
            raise ValueError(
                f"unknown device type {device_type!r}; expected one of {sorted(self.devstr2type)}"
            )
        self.device_type = device_type
        self.device_id = int(device_id)
        self._old_ctx: Optional["Context"] = None

    # -- identity ---------------------------------------------------------
    @property
    def device_typeid(self) -> int:
        return self.devstr2type[self.device_type]

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self) -> str:
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax binding ------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """Resolve to the backing ``jax.Device``.

        ``tpu``/``gpu`` map onto the accelerator backend if present; ``cpu`` and
        ``cpu_pinned`` map onto host devices. When the named backend is absent the
        context degrades to the default backend (so code written for ``tpu(0)`` runs
        unmodified under the CPU simulator used in tests).
        """
        want = {"cpu": "cpu", "cpu_pinned": "cpu", "gpu": None, "tpu": None}[self.device_type]
        devices = jax.devices() if want is None else _backend_devices(want)
        if not devices:
            devices = jax.devices()
        return devices[self.device_id % len(devices)]

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        Context._default_ctx.value = self._old_ctx
        return False

    # convenience mirrors of the reference API (python/mxnet/context.py:empty_cache etc.)
    def empty_cache(self):
        """No-op: XLA owns the device allocator; there is no framework pool to trim."""


def _backend_devices(platform: str):
    try:
        return jax.devices(platform)
    except RuntimeError:
        return []


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accepted for API parity; on this stack it aliases the accelerator backend."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """The first-class TPU context (the reference has no accelerator beyond CUDA gpu())."""
    return Context("tpu", device_id)


def current_context() -> Context:
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        # default to the accelerator if one exists, else cpu — unlike the reference
        # (which defaults to cpu(0)), a TPU-native framework should land tensors on
        # the chip by default.
        ctx = tpu(0) if jax.default_backend() not in ("cpu",) else cpu(0)
    return ctx


def num_devices(platform: Optional[str] = None) -> int:
    devs = jax.devices() if platform is None else _backend_devices(platform)
    return len(devs)


def num_gpus() -> int:
    """Parity shim for ``mx.context.num_gpus`` — counts accelerator devices."""
    n = num_devices()
    return 0 if jax.default_backend() == "cpu" else n


def num_tpus() -> int:
    return 0 if jax.default_backend() == "cpu" else num_devices()


def device_mesh(shape: Sequence[int], axis_names: Sequence[str]) -> "jax.sharding.Mesh":
    """Build a ``jax.sharding.Mesh`` over the available devices.

    This is the TPU-native replacement for the reference's flat device lists
    (``DataParallelExecutorGroup`` context lists, executor_group.py:143): parallelism is
    expressed as named mesh axes consumed by pjit shardings and shard_map collectives.
    """
    import numpy as np

    devices = np.asarray(jax.devices())
    need = int(np.prod(shape))
    if need > devices.size:
        raise ValueError(f"mesh shape {tuple(shape)} needs {need} devices, have {devices.size}")
    return jax.sharding.Mesh(devices[:need].reshape(shape), tuple(axis_names))
