"""Runtime-compiled device kernels from Python — ``mx.rtc`` capability parity.

The reference lets users hand the framework raw device-kernel source at runtime:
``rtc.CudaModule(source).get_kernel(name, signature).launch(args, ctx, grid,
block)`` compiles CUDA C via NVRTC (python/mxnet/rtc.py, include/mxnet/rtc.h:39
``CudaModule``). The TPU-native equivalent of "inline device code" is a **Pallas
kernel**: the module accepts Python source that defines Pallas kernel bodies
(Ref-in/Ref-out functions), compiles it in-process, and ``get_kernel`` wraps a
body in ``pl.pallas_call`` so it launches over a grid on the MXU/VPU — the same
escape hatch, targeting the TPU toolchain instead of NVRTC.

Differences from the reference, stated:
* the kernel language is Pallas (Python/JAX), not CUDA C — there is no NVRTC on
  TPU; Pallas IS the runtime kernel toolchain;
* ``launch(grid=...)`` maps to the pallas grid; the block dimension is expressed
  through BlockSpecs rather than thread blocks;
* kernels run under jit and compose with autograd like any other op (a CUDA
  kernel in the reference is opaque to autograd too).

On non-TPU backends kernels run in Pallas interpret mode (the deterministic
"NaiveEngine-style" path), so user kernels are testable on CPU.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasKernel:
    """One launchable kernel from a :class:`PallasModule` (``CudaKernel`` role).

    ``launch``/``__call__`` wraps the kernel body in ``pl.pallas_call`` with the
    given output shapes and (optional) grid/BlockSpecs, then applies it to the
    arrays. NDArray inputs are unwrapped; NDArray outputs returned.
    """

    def __init__(self, fn, name: str, interpret: Optional[bool]):
        self._fn = fn
        self.name = name
        self._interpret = interpret

    def launch(self, args: Sequence[Any], out_shapes,
               grid: Optional[Tuple[int, ...]] = None,
               in_specs=None, out_specs=None,
               interpret: Optional[bool] = None, **pallas_kwargs):
        """Run the kernel. ``out_shapes`` is a (shape, dtype) pair or a list of
        them (≈ the reference's signature declaring outputs); ``grid`` is the
        pallas grid (≈ grid_dims); BlockSpecs replace block_dims."""
        from jax.experimental import pallas as pl

        if interpret is None:
            interpret = self._interpret
        if interpret is None:
            interpret = jax.default_backend() != "tpu"

        # a single output is a (shape, dtype) pair; multiple outputs are a
        # list/tuple of such pairs (a dtype is never a tuple, which
        # disambiguates ((4,), f32) from (((4,), f32), ((4,), f32)))
        single = (isinstance(out_shapes, tuple) and len(out_shapes) == 2
                  and isinstance(out_shapes[0], (tuple, list))
                  and not isinstance(out_shapes[1], (tuple, list)))
        if single:
            out_shapes = [out_shapes]
        shape_structs = [jax.ShapeDtypeStruct(tuple(s), d)
                         for s, d in out_shapes]

        kwargs: Dict[str, Any] = dict(pallas_kwargs)
        if grid is not None:
            kwargs["grid"] = grid
        if in_specs is not None:
            kwargs["in_specs"] = in_specs
        if out_specs is not None:
            kwargs["out_specs"] = out_specs

        call = pl.pallas_call(
            self._fn,
            out_shape=shape_structs[0] if single else shape_structs,
            interpret=interpret, **kwargs)

        from .ndarray.ndarray import NDArray
        raw = [a.data if isinstance(a, NDArray) else jnp.asarray(a)
               for a in args]
        out = call(*raw)
        if single:
            return NDArray(out)
        return [NDArray(o) for o in out]

    __call__ = launch


class PallasModule:
    """Compile Pallas kernel source at runtime (``CudaModule`` role).

    ``source`` is Python text defining one or more kernel bodies — functions of
    ``(*input_refs, *output_refs)`` in Pallas style. It is executed in a
    namespace pre-seeded with ``jnp``, ``jax``, ``lax``, and ``pl`` (the NVRTC
    analogue: the toolchain headers are already included). ``exports`` limits
    which names are retrievable, like the reference's exports list.
    """

    def __init__(self, source: str, options: Sequence[str] = (),
                 exports: Sequence[str] = (), interpret: Optional[bool] = None):
        from jax import lax
        from jax.experimental import pallas as pl

        self._ns: Dict[str, Any] = {"jnp": jnp, "jax": jax, "lax": lax,
                                    "pl": pl}
        # options is accepted for API parity; Pallas has no compiler CLI flags
        self._exports = tuple(exports)
        self._interpret = interpret
        code = compile(source, "<mxtpu.rtc source>", "exec")
        exec(code, self._ns)

    def get_kernel(self, name: str, signature: str = "") -> PallasKernel:
        """Look up a kernel body by name. ``signature`` is accepted for
        reference-API compatibility and ignored: Pallas kernels carry their
        argument structure in the BlockSpecs/out_shape given at launch."""
        if self._exports and name not in self._exports:
            raise ValueError(f"kernel {name!r} not in exports {self._exports}")
        fn = self._ns.get(name)
        if fn is None or not callable(fn):
            raise ValueError(f"no kernel function {name!r} in module source")
        return PallasKernel(fn, name, self._interpret)


# The reference name, kept as an alias so `mx.rtc.CudaModule(...)` code finds
# the TPU equivalent with a clear error-free migration path.
CudaModule = PallasModule
