"""Collectives — the communication backbone (SURVEY.md §5 "distributed communication
backend"): one layer exposing allreduce/allgather/reducescatter/broadcast/barrier as
XLA collectives over a Mesh, replacing the reference's Comm tree / NCCL / ps-lite
stack (src/kvstore/comm.h, kvstore_nccl.h, kvstore_dist.h).

Two API levels:

* **array level** (used by KVStore dist mode): ``allreduce_array`` etc. operate on a
  replicated/sharded ``jax.Array`` and run a tiny pjit'd program whose collective XLA
  lowers onto ICI (in-slice) or DCN (cross-slice) automatically.
* **in-program level** (used inside shard_map'd training steps): ``psum``/
  ``all_gather``/``reduce_scatter``/``ppermute`` re-exports with the mesh axis name —
  these are what a sharded train step calls so XLA can overlap them with compute
  (the reference's push/pull priority-overlap trick, model.py:141-153, becomes XLA
  latency hiding).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, get_default_mesh

__all__ = ["allreduce", "allreduce_array", "allgather_array", "broadcast_array",
           "reduce_scatter_array", "all_to_all_array", "a2a_impl", "barrier",
           "psum", "pmean", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all", "shard_map_compat"]


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = False):
    """Version-portable shard_map: jax ≥ 0.5 exposes top-level
    ``jax.shard_map(..., check_vma=)``; 0.4.x ships
    ``jax.experimental.shard_map.shard_map(..., check_rep=)``. Every
    shard_map in the framework (collectives, ring attention, MoE dispatch,
    GPipe) routes through here so the dual-API dance lives in ONE place."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)

# -- in-program collectives (use inside shard_map/pjit bodies) --------------
psum = lax.psum
pmean = lax.pmean
all_gather = lax.all_gather
ppermute = lax.ppermute
all_to_all = lax.all_to_all


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=tiled)


# -- array-level collectives ------------------------------------------------

def _shard_map_1d(fn, mesh: Mesh, in_spec, out_spec):
    return shard_map_compat(fn, mesh, in_spec, out_spec)


def allreduce_array(x, mesh: Optional[Mesh] = None, op: str = "sum"):
    """All-reduce a (replicated or dp-sharded) array over the mesh's first axis.

    For a fully-replicated single-process array this is the identity for 'sum' over
    ranks=1; in multi-process (jax.distributed) it reduces across processes.
    """
    mesh = mesh or get_default_mesh()
    axis = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)

    def _psum(v):
        r = lax.psum(v, axis)
        return r / mesh.shape[axis] if op == "mean" else r

    fn = shard_map_compat(_psum, mesh, P(), P())

    # Resilience seam + retry at the array-level entry (the path kvstore and
    # barrier() ride): a transient backend failure here — the "one
    # UNAVAILABLE erased a bench round" incident — is retried; the injected
    # `collective` fault reproduces it on CPU tier-1, where the
    # cross-process short-circuits above never fire.
    from ..resilience import fault_point, retry_transient

    def _run():
        fault_point("collective")
        return fn(jnp.asarray(x))

    return retry_transient(_run, label="collective.allreduce")


allreduce = allreduce_array


def allgather_array(x, mesh: Optional[Mesh] = None, axis: int = 0):
    """Gather dp-sharded rows into the full array on every device."""
    mesh = mesh or get_default_mesh()
    ax_name = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)
    spec = [None] * jnp.ndim(x)
    spec[axis] = ax_name

    def _ag(v):
        return lax.all_gather(v, ax_name, axis=axis, tiled=True)

    fn = shard_map_compat(_ag, mesh, P(*spec), P())
    return fn(jnp.asarray(x))


def reduce_scatter_array(x, mesh: Optional[Mesh] = None, axis: int = 0):
    mesh = mesh or get_default_mesh()
    ax_name = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)
    spec = [None] * jnp.ndim(x)
    spec[axis] = ax_name

    def _rs(v):
        return lax.psum_scatter(v, ax_name, scatter_dimension=axis, tiled=True)

    fn = shard_map_compat(_rs, mesh, P(), P(*spec))
    return fn(jnp.asarray(x))


_A2A_IMPLS = ("jit_reshard", "shard_map")
_a2a_programs = None


def a2a_impl() -> str:
    """Active array-level all_to_all lowering, selected by ``MXTPU_A2A_IMPL``.

    * ``jit_reshard`` (default) — the fast path the PR 8 ``all_to_all_probe``
      proved: express the exchange as a sharding-spec flip inside one jitted
      identity and let GSPMD emit the native all-to-all. The explicit
      ``shard_map``+``lax.all_to_all`` lowering was ~12.6× slower for the same
      logical op (VERDICT: 64 MB a2a at 9,582 ms vs 1,117 ms allreduce).
    * ``shard_map`` — the legacy explicit lowering, kept for A/B comparison.
    """
    impl = os.environ.get("MXTPU_A2A_IMPL", "jit_reshard").strip().lower()
    if impl not in _A2A_IMPLS:
        raise ValueError(f"MXTPU_A2A_IMPL={impl!r}: expected one of {_A2A_IMPLS}")
    return impl


def _a2a_program_cache():
    # lazy: collectives loads very early; step_cache registration can wait
    global _a2a_programs
    if _a2a_programs is None:
        from ..step_cache import ProgramCache
        _a2a_programs = ProgramCache("a2a_reshard")
    return _a2a_programs


def all_to_all_array(x, mesh: Optional[Mesh] = None, split_axis: int = 1,
                     concat_axis: int = 0, *, axis_name: Optional[str] = None,
                     tiled: bool = True, impl: Optional[str] = None):
    """Transpose shard ownership: each device scatters its ``split_axis``
    slices to peers and concatenates what it receives along ``concat_axis``
    (the Ulysses/MoE dispatch primitive). ``x`` is sharded on ``concat_axis``
    in, sharded on ``split_axis`` out.

    Two forms, so every all-to-all in the framework routes through ONE place:

    * **in-program** (``axis_name`` given): call from inside a shard_map body —
      dispatches straight to ``lax.all_to_all`` over that axis (``tiled``
      honored). MoE dispatch and Ulysses head/sequence exchange use this.
    * **array-level** (no ``axis_name``): operates on a global ``jax.Array``
      over the mesh's first axis. The lowering is selected by ``impl`` /
      ``MXTPU_A2A_IMPL`` (see :func:`a2a_impl`): the default ``jit_reshard``
      exploits that the tiled exchange is semantically a pure reshard — the
      global array is unchanged, only its sharding flips from
      ``concat_axis`` to ``split_axis`` — so a jitted spec flip lets GSPMD
      emit the native all-to-all instead of the degenerate shard_map lowering.
      Compiled programs are cached per (mesh, shape, dtype, axes) signature.
    """
    if axis_name is not None:
        return lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)

    mesh = mesh or get_default_mesh()
    ax_name = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    in_spec = [None] * x.ndim
    in_spec[concat_axis] = ax_name
    out_spec = [None] * x.ndim
    out_spec[split_axis] = ax_name

    chosen = impl or a2a_impl()
    if chosen not in _A2A_IMPLS:
        raise ValueError(f"all_to_all_array impl={chosen!r}: expected one of "
                         f"{_A2A_IMPLS}")
    key = (chosen, mesh, x.shape, str(x.dtype), split_axis, concat_axis)

    if chosen == "jit_reshard":
        in_sh = NamedSharding(mesh, P(*in_spec))
        out_sh = NamedSharding(mesh, P(*out_spec))

        def _build_reshard():
            def _flip(v):
                v = lax.with_sharding_constraint(v, in_sh)
                return lax.with_sharding_constraint(v, out_sh)
            return jax.jit(_flip, out_shardings=out_sh)

        fn = _a2a_program_cache().get_or_build(key, _build_reshard)
        return fn(x)

    def _build_shard_map():
        def _a2a(v):
            return lax.all_to_all(v, ax_name, split_axis=split_axis,
                                  concat_axis=concat_axis, tiled=True)
        return shard_map_compat(_a2a, mesh, P(*in_spec), P(*out_spec))

    fn = _a2a_program_cache().get_or_build(key, _build_shard_map)
    return fn(x)


def broadcast_array(x, mesh: Optional[Mesh] = None, root: int = 0):
    """Broadcast root's value to all devices (device_put with replicated sharding)."""
    mesh = mesh or get_default_mesh()
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))


def barrier(mesh: Optional[Mesh] = None):
    """Block until all devices/processes reach this point (ps::Postoffice barrier
    parity): a 1-element psum everyone must contribute to."""
    mesh = mesh or get_default_mesh()
    out = allreduce_array(jnp.ones(()), mesh)
    jax.block_until_ready(out)
    return float(out)


# -- cross-process collectives (kvstore dist_sync backbone) -----------------
# The reference's worker→server push/pull (kvstore_dist.h, ps-lite/ZMQ) becomes one
# XLA collective over a process-spanning mesh: each process contributes its local
# value on a leading "proc" axis; the reduction rides DCN/ICI.

def _process_mesh() -> Mesh:
    import numpy as np
    devs = jax.devices()
    nproc = jax.process_count()
    per = len(devs) // nproc
    # one device per process is enough for host-value reduction
    picked = [d for d in devs if d.id % per == 0] if per > 1 else devs
    return Mesh(np.array(picked[:nproc]), ("proc",))


def _process_exchange(x, body):
    """Shared cross-process plumbing: stack each rank's host value on a 'proc'
    axis, run `body` replicated, return the host-local result. Both
    allreduce_processes and allgather_processes ride this one path so
    transport fixes land once. Wall time + payload bytes land in the
    profiler's comm counters (``get_comm_stats().collective_*``) — the
    measured half of the comm-accounting story (the in-program ZeRO
    collectives are accounted analytically per step)."""
    import time
    import numpy as np
    from .. import profiler
    from ..observability import tracer
    from ..resilience import fault_point, retry_transient
    t0 = time.perf_counter()
    local = np.asarray(jax.device_get(jnp.asarray(x)))[None]

    def _run():
        # seam + retry around the whole exchange: DCN flakes surface here as
        # backend UNAVAILABLE, and re-running the collective is idempotent
        # (every rank re-contributes the same host value)
        fault_point("exchange")
        with tracer.span("comm/exchange", cat="comm",
                         args={"bytes": int(local.nbytes)}):
            mesh = _process_mesh()
            sh = NamedSharding(mesh, P("proc"))
            arr = jax.make_array_from_process_local_data(sh, local)
            fn = jax.jit(body, out_shardings=NamedSharding(mesh, P()))
            out = fn(arr)
            jax.block_until_ready(out)
            return jnp.asarray(jax.device_get(out))

    res = retry_transient(_run, label="collective.exchange")
    profiler.record_collective((time.perf_counter() - t0) * 1e3, local.nbytes)
    return res


def allreduce_processes(x, op: str = "sum"):
    """Reduce a per-process host value across ALL processes; returns a host-local
    array every rank can read (dist_sync push semantics, kvstore_dist_server.h:283)."""
    nproc = jax.process_count()
    if nproc == 1:
        return jnp.asarray(x)

    def _sum(a):
        s = jnp.sum(a, axis=0)
        return s / nproc if op == "mean" else s

    return _process_exchange(x, _sum)


def allreduce_rowsparse_processes(indices, values, num_rows: int):
    """Cross-process row-sparse sum WITHOUT densifying: returns
    ``(union_rows, summed_values)`` where payload across the wire is
    O(union rows), not O(dense size).

    Reference: ``kvstore_dist.h:436-510`` DataHandleRowSparse /
    EncodeRowSparseKey ship only live rows over ps-lite. Here the exchange is
    three static-shape XLA collectives:

    1. allgather each rank's (count-padded) row ids — O(max_rows × nproc) ints;
    2. every rank deterministically computes the sorted union on host;
    3. allreduce a (union_padded × row_width) value slab — O(union rows).

    The union slab is padded to the next power of two so XLA recompiles
    O(log num_rows) distinct programs, not one per distinct union size
    (the reference's bucketing trick applied to comm shapes).
    """
    import numpy as np
    idx = np.asarray(jax.device_get(jnp.asarray(indices))).astype(np.int64)
    vals = np.asarray(jax.device_get(jnp.asarray(values)))
    if jax.process_count() == 1:
        return jnp.asarray(idx), jnp.asarray(vals)

    # 1) agree on a common padded index length (gather per-rank counts — nproc
    # scalars), then allgather the padded row ids. Pad marker is num_rows (an
    # invalid row id). nmax is pow2-bucketed like the value slab so varying
    # live-row counts reuse compiled programs.
    counts = np.asarray(jax.device_get(allgather_processes(
        jnp.asarray([np.int32(len(idx))]))))
    nmax = 1
    while nmax < max(1, int(counts.max())):
        nmax *= 2
    nmax = min(nmax, num_rows)
    pad = np.full((nmax,), num_rows, np.int32)
    pad[:len(idx)] = idx
    all_idx = np.asarray(jax.device_get(allgather_processes(
        jnp.asarray(pad)))).astype(np.int64)

    # 2) deterministic union on every rank
    union = np.unique(all_idx.reshape(-1))
    union = union[union < num_rows]
    # bucket the slab length: next power of two, so comm programs are reused
    cap = 1
    while cap < max(1, len(union)):
        cap *= 2
    cap = min(cap, num_rows)

    # 3) scatter local rows into the union slab, allreduce the slab
    slab = np.zeros((cap,) + vals.shape[1:], vals.dtype)
    pos = np.searchsorted(union, idx)
    np.add.at(slab, pos, vals)        # accumulate — local dup rows stay correct
    summed = allreduce_processes(jnp.asarray(slab))
    return jnp.asarray(union), jnp.asarray(summed)[:len(union)]


def allgather_processes(x):
    """Concatenate each process's host value along a new leading axis
    (every rank receives all contributions)."""
    if jax.process_count() == 1:
        return jnp.asarray(x)[None]
    return _process_exchange(x, lambda a: a)


def broadcast_processes(x, root: int = 0):
    """Every rank receives root's value (ps-lite init-broadcast parity)."""
    import numpy as np
    if jax.process_count() == 1:
        return jnp.asarray(x)
    xs = np.asarray(jax.device_get(jnp.asarray(x)))
    contrib = xs if jax.process_index() == root else np.zeros_like(xs)
    return allreduce_processes(contrib)


def process_barrier():
    """Block until every process arrives (ps::Postoffice::Barrier parity)."""
    out = allreduce_processes(jnp.ones(()))
    return float(out)
