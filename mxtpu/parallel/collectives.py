"""Collectives — the communication backbone (SURVEY.md §5 "distributed communication
backend"): one layer exposing allreduce/allgather/reducescatter/broadcast/barrier as
XLA collectives over a Mesh, replacing the reference's Comm tree / NCCL / ps-lite
stack (src/kvstore/comm.h, kvstore_nccl.h, kvstore_dist.h).

Two API levels:

* **array level** (used by KVStore dist mode): ``allreduce_array`` etc. operate on a
  replicated/sharded ``jax.Array`` and run a tiny pjit'd program whose collective XLA
  lowers onto ICI (in-slice) or DCN (cross-slice) automatically.
* **in-program level** (used inside shard_map'd training steps): ``psum``/
  ``all_gather``/``reduce_scatter``/``ppermute`` re-exports with the mesh axis name —
  these are what a sharded train step calls so XLA can overlap them with compute
  (the reference's push/pull priority-overlap trick, model.py:141-153, becomes XLA
  latency hiding).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, get_default_mesh

__all__ = ["allreduce", "allreduce_array", "allgather_array", "broadcast_array",
           "reduce_scatter_array", "barrier", "psum", "pmean", "all_gather",
           "reduce_scatter", "ppermute", "all_to_all"]

# -- in-program collectives (use inside shard_map/pjit bodies) --------------
psum = lax.psum
pmean = lax.pmean
all_gather = lax.all_gather
ppermute = lax.ppermute
all_to_all = lax.all_to_all


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension,
                            tiled=tiled)


# -- array-level collectives ------------------------------------------------

def _shard_map_1d(fn, mesh: Mesh, in_spec, out_spec):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec)


def allreduce_array(x, mesh: Optional[Mesh] = None, op: str = "sum"):
    """All-reduce a (replicated or dp-sharded) array over the mesh's first axis.

    For a fully-replicated single-process array this is the identity for 'sum' over
    ranks=1; in multi-process (jax.distributed) it reduces across processes.
    """
    mesh = mesh or get_default_mesh()
    axis = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)

    def _psum(v):
        r = lax.psum(v, axis)
        return r / mesh.shape[axis] if op == "mean" else r

    fn = jax.shard_map(_psum, mesh=mesh, in_specs=P(), out_specs=P(),
                       check_vma=False)
    return fn(jnp.asarray(x))


allreduce = allreduce_array


def allgather_array(x, mesh: Optional[Mesh] = None, axis: int = 0):
    """Gather dp-sharded rows into the full array on every device."""
    mesh = mesh or get_default_mesh()
    ax_name = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)
    spec = [None] * jnp.ndim(x)
    spec[axis] = ax_name

    def _ag(v):
        return lax.all_gather(v, ax_name, axis=axis, tiled=True)

    fn = jax.shard_map(_ag, mesh=mesh, in_specs=P(*spec), out_specs=P(),
                       check_vma=False)
    return fn(jnp.asarray(x))


def reduce_scatter_array(x, mesh: Optional[Mesh] = None, axis: int = 0):
    mesh = mesh or get_default_mesh()
    ax_name = mesh.axis_names[0]
    if mesh.devices.size == 1:
        return jnp.asarray(x)
    spec = [None] * jnp.ndim(x)
    spec[axis] = ax_name

    def _rs(v):
        return lax.psum_scatter(v, ax_name, scatter_dimension=axis, tiled=True)

    fn = jax.shard_map(_rs, mesh=mesh, in_specs=P(), out_specs=P(*spec),
                       check_vma=False)
    return fn(jnp.asarray(x))


def broadcast_array(x, mesh: Optional[Mesh] = None, root: int = 0):
    """Broadcast root's value to all devices (device_put with replicated sharding)."""
    mesh = mesh or get_default_mesh()
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))


def barrier(mesh: Optional[Mesh] = None):
    """Block until all devices/processes reach this point (ps::Postoffice barrier
    parity): a 1-element psum everyone must contribute to."""
    mesh = mesh or get_default_mesh()
    out = allreduce_array(jnp.ones(()), mesh)
    jax.block_until_ready(out)
    return float(out)
