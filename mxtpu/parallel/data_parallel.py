"""Data-parallel training over a mesh — the TPU-native replacement for the reference's
``DataParallelExecutorGroup`` + KVStore reduce (SURVEY.md §2.3 row "DP, single
machine"): instead of splitting a batch into per-GPU executors and reducing grads
through a Comm tree, the batch is **sharded** over the ``dp`` mesh axis and one jitted
step runs SPMD — XLA inserts the gradient all-reduce over ICI and overlaps it with
backward compute (the reference's priority-overlap trick, for free).

``DataParallelTrainer`` wraps a Gluon block + optimizer into such a step.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import numpy as np

from .. import autograd
from .. import ndarray as nd_mod
from ..ndarray.ndarray import NDArray
from ..step_cache import build_update_all, cache_stats
from . import fsdp as fsdp_mod
from . import zero as zero_mod
from .mesh import (Mesh, data_axis_names, data_size, dp_size,
                   fsdp_axis_name, fsdp_size, get_default_mesh)

__all__ = ["shard_batch", "replicate", "place", "DataParallelTrainer"]


def _place(raw, sharding: NamedSharding):
    """Host→mesh placement that works in both single- and multi-process runs.

    Multi-process (jax.distributed): a process can only device_put to its own
    devices, so each rank contributes its LOCAL slice and JAX assembles the global
    array (the SPMD per-host-feed convention; replaces the reference's per-worker
    batch slicing in executor_group.py:281-310)."""
    import jax.numpy as _jnp
    raw = _jnp.asarray(raw)
    if jax.process_count() > 1 and any(
            not d.process_index == jax.process_index()
            for d in sharding.mesh.devices.flat):
        import numpy as np
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(jax.device_get(raw)))
    return jax.device_put(raw, sharding)


# public alias: the checkpoint subsystem restores arrays through the SAME
# placement path the training step feeds through (per-host local slices
# assemble into the global array under jax.distributed)
place = _place


def shard_batch(array, mesh: Optional[Mesh] = None, axis: int = 0) -> NDArray:
    """Place a host batch as a dp-sharded jax.Array (≈ decide_slices/_split_input_slice,
    executor_group.py:281-310 — but one logical array, no per-device copies).

    Multi-process: ``array`` is this rank's LOCAL batch shard.

    An array already committed with the target sharding (e.g. staged by a
    ``device_feed.DeviceFeed`` ahead of the step) is returned as-is — the
    step path never double-``device_put``s resident inputs."""
    mesh = mesh or get_default_mesh()
    spec = [None] * (array.ndim if hasattr(array, "ndim") else len(array.shape))
    axes = data_axis_names(mesh)
    spec[axis] = axes if len(axes) > 1 else axes[0]
    raw = array.data if isinstance(array, NDArray) else jnp.asarray(array)
    target = NamedSharding(mesh, P(*spec))
    if isinstance(raw, jax.Array) and getattr(raw, "committed", False) \
            and raw.sharding == target:
        return array if isinstance(array, NDArray) else NDArray(raw)
    return NDArray(_place(raw, target))


def replicate(array, mesh: Optional[Mesh] = None) -> NDArray:
    mesh = mesh or get_default_mesh()
    raw = array.data if isinstance(array, NDArray) else jnp.asarray(array)
    return NDArray(_place(raw, NamedSharding(mesh, P())))


class DataParallelTrainer:
    """Sharded training step: params replicated, batch dp-sharded, grads psum'd.

    Usage::

        dpt = DataParallelTrainer(net, loss_fn, optimizer, mesh)
        loss = dpt.step(x_batch, y_batch)   # one jitted SPMD step

    The whole fwd+bwd+update is ONE XLA program: gradient all-reduce rides ICI and
    overlaps backward; optimizer update is fused in (donated buffers).
    """

    def __init__(self, block, loss_fn, optimizer, mesh: Optional[Mesh] = None,
                 param_shardings=None, remat: bool = False,
                 micro_batches: int = 1, zero: Optional[bool] = None,
                 compression_params: Optional[dict] = None):
        """``param_shardings`` is the gluon-integrated model-parallel hook (the
        TPU-native replacement for the reference's ``ctx_group``/``group2ctx`` layer
        placement, graph_executor.cc:408): a dict mapping parameter-name suffixes to
        ``PartitionSpec``s, or a callable ``name -> PartitionSpec | None``. Unlisted
        params are replicated. XLA/GSPMD inserts the tp collectives automatically.

        ``remat=True`` wraps the loss in ``jax.checkpoint`` (rematerialization:
        trade one extra forward's FLOPs for not keeping activations alive
        across fwd→bwd — the reference's mirror/memonger capability). Use when
        activation memory approaches HBM capacity (large batch/sequence);
        benchmark/python/mfu_probe.py quantifies the tradeoff.

        ``micro_batches=k`` accumulates gradients over k micro-batches inside
        ONE jitted step (a ``lax.scan``): activation memory is that of
        batch/k while the optimizer sees the full-batch gradient — the
        measured cure for the large-batch HBM-capacity cliff (mfu_probe:
        b512 peaks at 15.3/16 GB HBM and loses 8% throughput to scheduling
        pressure; k=4 keeps the b128 working set). Micro-batches take every
        k-th row so each stays evenly dp-sharded.

        ``zero`` selects the ZeRO gradient/update path (default: the
        ``MXTPU_ZERO`` env, on unless ``=0``), staged by ``MXTPU_ZERO_STAGE``:
        gradients resolve per-param as reduce-scatters over the named data
        axes into packed buckets, optimizer slots live 1/N-sharded, updated
        params are all-gathered back (parallel/zero.py). Works on any mesh —
        tensor-parallel-sharded params keep the per-param update; at stage 3
        shardable params are instead RESIDENT 1/N on the ``fsdp`` axis
        (parallel/fsdp.py). ``compression_params`` (KVStore
        ``set_gradient_compression`` dict: type 2bit|fp16|bf16) lowers the
        bucket payload with an error-feedback residual."""
        self.block = block
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh or get_default_mesh()
        self.param_shardings = param_shardings
        self.remat = remat
        self.micro_batches = int(micro_batches)
        self.zero = (zero_mod.zero_enabled() if zero is None else bool(zero)) \
            and zero_mod.supports_zero(optimizer)
        self.stage = fsdp_mod.zero_stage() if self.zero else 0
        if compression_params is not None:
            zero_mod.comm_dtype_of(compression_params)  # validate the kind
        self._compression_params = compression_params
        self._step_fn = None
        self._params: List = []
        self._states: List = []
        self._zero_layout = None
        self._zero_states: List = []
        self._zero_residuals: List = []
        self._stats = cache_stats("data_parallel_step")

    def _spec_for(self, name) -> P:
        if self.param_shardings is None:
            return P()
        if callable(self.param_shardings):
            return self.param_shardings(name) or P()
        for suffix, spec in self.param_shardings.items():
            if name.endswith(suffix):
                return spec
        return P()

    def _collect(self, x_example):
        # ensure deferred params materialize
        with autograd.predict_mode():
            self.block(x_example)
        named = list(self.block.collect_params().items())
        self._param_names = [n for n, p in named
                             if p._data is not None and p.grad_req != "null"]
        self._param_handles = [p for n, p in named
                               if p._data is not None and p.grad_req != "null"]
        self._aux_handles = [p for n, p in named
                             if p._data is not None and p.grad_req == "null"]
        # place across the mesh: replicated unless a tp sharding was
        # requested; at stage 3 the fsdp axis composes into every spec with
        # an eligible (free, divisible) dimension — those params are RESIDENT
        # 1/N and XLA all-gathers them just-in-time per layer
        self._param_sh = [NamedSharding(self.mesh, self._spec_for(n))
                          for n in self._param_names]
        if self.zero and self.stage >= 3:
            composed = fsdp_mod.fsdp_param_specs(
                [p.data().shape for p in self._param_handles],
                [sh.spec for sh in self._param_sh], self.mesh)
            self._param_sh = [
                NamedSharding(self.mesh, c) if c is not None else sh
                for c, sh in zip(composed, self._param_sh)]
        for p, sh in zip(self._param_handles, self._param_sh):
            p._data._set_data(_place(p.data().data, sh))
        for p in self._aux_handles:
            p._data._set_data(_place(p.data().data, NamedSharding(self.mesh, P())))
        repl = NamedSharding(self.mesh, P())
        if self.zero:
            # replicated params bucket into data-sharded flat slots; tp- and
            # fsdp-sharded params keep the per-param update below (their
            # slots follow the param's sharding, so fsdp slots are 1/N too)
            eligible = [sh.spec == P() for sh in self._param_sh]
            raws = [p.data().data for p in self._param_handles]
            self._zero_layout = zero_mod.ZeroLayout(
                raws,
                [getattr(p, "lr_mult", 1.0) for p in self._param_handles],
                [getattr(p, "wd_mult", 1.0) for p in self._param_handles],
                data_size(self.mesh), eligible=eligible)
            self._zero_states, self._zero_residuals = zero_mod.init_zero_states(
                self.optimizer, self._zero_layout, raws, self.mesh,
                with_residual=self._compression_params is not None)
            self._zero_state_sh = zero_mod.state_shardings(
                self._zero_layout, self._zero_states, self.mesh)
            passthrough = set(self._zero_layout.passthrough)
        else:
            passthrough = set(range(len(self._param_handles)))
        self._states = [
            self.optimizer.create_state(i, p.data()) if i in passthrough
            else ()
            for i, p in enumerate(self._param_handles)]
        # optimizer state follows its param's sharding (same-shape moments etc.)
        self._states = [tuple(_place(
            s, sh if getattr(s, "shape", None) == p.data().shape else repl)
            for s in st)
            for p, sh, st in zip(self._param_handles, self._param_sh, self._states)]
        self._state_sh = [tuple(
            sh if getattr(s, "shape", None) == p.data().shape else repl
            for s in st)
            for p, sh, st in zip(self._param_handles, self._param_sh, self._states)]
        self._record_memory()

    def _record_memory(self):
        """Per-device param/grad/slot byte accounting (profiler
        ``get_memory_stats``), from the actual placed shardings."""
        params = [p.data().data for p in self._param_handles]
        slots = [s for st in list(self._states) + list(self._zero_states)
                 for s in (st or ()) if hasattr(s, "dtype")]
        slots += [r for r in self._zero_residuals if r is not None]
        grad_bytes = sum(
            int(np.prod(p.shape)) * np.dtype(str(p.dtype)).itemsize
            for p in params)
        fsdp_mod.measure_memory(self.stage, self.mesh, params, slots,
                                grad_bytes)

    def _build(self):
        block, loss_fn, opt = self.block, self.loss_fn, self.optimizer
        param_handles = self._param_handles
        aux_handles = self._aux_handles
        from .. import rng as rng_mod
        # the per-param optimizer application is the SAME inlined
        # preprocess+kernel composition the fused Module step uses
        # (step_cache.build_update_all) — one shared code path for every
        # whole-step compile in the framework
        lr_mults = [getattr(p, "lr_mult", 1.0) for p in param_handles]
        wd_mults = [getattr(p, "wd_mult", 1.0) for p in param_handles]
        # per-param updates apply only to the passthrough set (everything,
        # when ZeRO is off; the tp-sharded leftovers when it is on)
        pt = list(self._zero_layout.passthrough) if self.zero \
            else list(range(len(param_handles)))
        update_pt = build_update_all(
            opt, [lr_mults[i] for i in pt], [wd_mults[i] for i in pt])
        zero_update = zero_mod.build_zero_update(
            opt, self._zero_layout, self.mesh,
            comm_dtype=zero_mod.comm_dtype_of(self._compression_params),
            compression_params=self._compression_params) if self.zero else None
        # ZeRO-2: micro-batch accumulation holds packed 1/N bucket SHARDS —
        # each micro-gradient reduce-scatters into its shard inside the scan,
        # so no replicated gradient buffer ever materializes for bucketed
        # params
        stage2_acc = (zero_update is not None and self.stage >= 2
                      and self.micro_batches > 1
                      and self._zero_layout.buckets)
        pack_grads = zero_mod.build_grad_pack(self._zero_layout, self.mesh) \
            if stage2_acc else None
        zshard = self._zero_layout.shard_spec(self.mesh) if self.zero else None

        def step(params, auxs, states, zstates, zres, x, y, lr, wd, rescale,
                 clip, key, t):
            provider = rng_mod.push_trace_provider(key)
            saved = [p._data._data for p in param_handles]
            saved_aux = [p._data._data for p in aux_handles]
            try:
                def loss_on(ps, auxs_in, xb, yb):
                    for p, v in zip(param_handles, ps):
                        p._data._data = v
                        p._data._version += 1
                    for p, v in zip(aux_handles, auxs_in):
                        p._data._data = v
                        p._data._version += 1
                    with autograd.pause(train_mode=True):
                        out = block(nd_mod.NDArray(xb))
                        loss = loss_fn(out, nd_mod.NDArray(yb))
                    new_auxs = [p._data._data for p in aux_handles]
                    return jnp.mean(loss.data), new_auxs

                k = self.micro_batches
                if k > 1:
                    # gradient accumulation: scan over k micro-batches, each
                    # taking every k-th row (stays evenly dp-sharded);
                    # activation working set shrinks k-fold, the optimizer
                    # sees the mean full-batch gradient
                    def loss_of(ps, auxs_in, xb, yb):
                        f = (jax.checkpoint(loss_on) if self.remat
                             else loss_on)
                        return f(ps, auxs_in, xb, yb)

                    xs = jnp.swapaxes(
                        x.reshape((-1, k) + x.shape[1:]), 0, 1)
                    ys = jnp.swapaxes(
                        y.reshape((-1, k) + y.shape[1:]), 0, 1)

                    if pack_grads is not None:
                        # ZeRO-2 carry: packed bucket shards (1/N resident)
                        # plus full f32 grads ONLY for the passthrough set
                        def body(carry, xy):
                            pacc, gpt, lacc, auxs_c = carry
                            xb, yb = xy
                            (lv, new_aux), g = jax.value_and_grad(
                                loss_of, has_aux=True)(list(params), auxs_c,
                                                       xb, yb)
                            pk = pack_grads(g)
                            pacc = [a + q for a, q in zip(pacc, pk)]
                            gpt = [a + g[i].astype(jnp.float32)
                                   for a, i in zip(gpt, pt)]
                            return (pacc, gpt, lacc + lv, new_aux), None

                        init = ([jax.lax.with_sharding_constraint(
                                    jnp.zeros((b.padded,), jnp.float32),
                                    zshard)
                                 for b in self._zero_layout.buckets],
                                [jnp.zeros(params[i].shape, jnp.float32)
                                 for i in pt],
                                jnp.zeros((), jnp.float32), list(auxs))
                        (psum_b, gpt_sum, lsum, new_auxs), _ = jax.lax.scan(
                            body, init, (xs, ys))
                        packed = [p / k for p in psum_b]
                        grads = [None] * len(params)
                        for j, i in enumerate(pt):
                            grads[i] = gpt_sum[j] / k
                        loss_val = lsum / k
                    else:
                        def body(carry, xy):
                            gacc, lacc, auxs_c = carry
                            xb, yb = xy
                            (lv, new_aux), g = jax.value_and_grad(
                                loss_of, has_aux=True)(list(params), auxs_c,
                                                       xb, yb)
                            # accumulate in f32: summing k similar-magnitude
                            # bf16 grads in bf16 would compound rounding vs
                            # the k=1 step
                            gacc = [a + gi.astype(jnp.float32)
                                    for a, gi in zip(gacc, g)]
                            return (gacc, lacc + lv, new_aux), None

                        init = ([jnp.zeros(p.shape, jnp.float32)
                                 for p in params],
                                jnp.zeros((), jnp.float32), list(auxs))
                        (gsum, lsum, new_auxs), _ = jax.lax.scan(
                            body, init, (xs, ys))
                        grads = [g / k for g in gsum]  # f32; cast per param
                        packed = None
                        loss_val = lsum / k
                else:
                    def loss_of(ps):
                        f = (jax.checkpoint(loss_on) if self.remat
                             else loss_on)
                        return f(ps, list(auxs), x, y)

                    (loss_val, new_auxs), grads = jax.value_and_grad(
                        loss_of, has_aux=True)(list(params))
                    packed = None
                if zero_update is not None:
                    new_params, new_zstates, new_zres = zero_update(
                        list(params), list(grads), zstates, zres,
                        lr, wd, rescale, clip, t, packed_grads=packed)
                else:
                    new_params = list(params)
                    new_zstates, new_zres = zstates, zres
                new_states = [()] * len(param_handles)
                if pt:
                    sub_w, sub_st = update_pt(
                        [new_params[i] for i in pt], [grads[i] for i in pt],
                        [states[i] for i in pt], lr, wd, rescale, clip, t)
                    for j, i in enumerate(pt):
                        new_params[i] = sub_w[j]
                        new_states[i] = sub_st[j]
                return (new_params, new_auxs, new_states, new_zstates,
                        new_zres, loss_val)
            finally:
                for p, v in zip(param_handles, saved):
                    p._data._data = v
                for p, v in zip(aux_handles, saved_aux):
                    p._data._data = v
                rng_mod.pop_trace_provider()

        repl = NamedSharding(self.mesh, P())
        axes = data_axis_names(self.mesh)
        batch = NamedSharding(self.mesh,
                              P(axes if len(axes) > 1 else axes[0]))
        zstate_sh = getattr(self, "_zero_state_sh", []) if self.zero else []
        zres_sh = [self._zero_layout.shard_spec(self.mesh)
                   if r is not None else None
                   for r in self._zero_residuals] if self.zero else []
        # NB: no donation — optimizer states may alias the same zero buffer (e.g.
        # Adam's (m, v)) and XLA rejects donating one buffer twice; buffers are
        # reclaimed by refcount anyway since the handles are swapped after the call.
        self._step_fn = jax.jit(
            step,
            in_shardings=(self._param_sh, repl, self._state_sh, zstate_sh,
                          zres_sh, batch, batch, repl, repl, repl, repl, repl,
                          None),
            out_shardings=(self._param_sh, repl, self._state_sh, zstate_sh,
                           zres_sh, repl))

    def step_async(self, x, y) -> NDArray:
        """One SPMD train step; returns the loss WITHOUT a host sync, so callers
        can keep the device queue full (JAX async dispatch ≈ the reference
        engine's lazy push; WaitToRead happens when the caller materializes the
        loss)."""
        x = x if isinstance(x, NDArray) else nd_mod.array(x)
        y = y if isinstance(y, NDArray) else nd_mod.array(y)
        if self._step_fn is None:
            self._stats.miss()
            self._collect(x)
            self._build()
            self._t = 0
        else:
            self._stats.hit()
        if self.micro_batches > 1 and x.shape[0] % self.micro_batches:
            raise ValueError(
                f"batch size {x.shape[0]} is not divisible by "
                f"micro_batches={self.micro_batches}; pad or drop the tail "
                f"batch (ImageRecordIter marks it with .pad)")
        xs = shard_batch(x, self.mesh).data
        ys = shard_batch(y, self.mesh).data
        self._t += 1
        opt = self.optimizer
        lr = jnp.asarray(opt.learning_rate, jnp.float32)
        wd = jnp.asarray(opt.wd, jnp.float32)
        # grads are mean-loss grads already; rescale stays 1 (clip honors the
        # optimizer's clip_gradient, a static variant inside update_all)
        rescale = jnp.float32(1.0)
        clip = jnp.float32(opt.clip_gradient
                           if opt.clip_gradient is not None else 0.0)
        key = jax.random.key(self._t)
        params = [p.data().data for p in self._param_handles]
        auxs = [p.data().data for p in self._aux_handles]
        args = (params, auxs, self._states, self._zero_states,
                self._zero_residuals, xs, ys, lr, wd, rescale, clip,
                key, self._t)
        # keep only avals (shape/dtype) for cost_analysis — holding the real
        # arrays would pin the previous step's buffers in HBM
        self._last_avals = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") else a, args)
        (new_params, new_auxs, new_states, new_zstates, new_zres,
         loss) = self._step_fn(*args)
        for p, v in zip(self._param_handles, new_params):
            p._data._data = v
            p._data._version += 1
        for p, v in zip(self._aux_handles, new_auxs):
            p._data._data = v
            p._data._version += 1
        self._states = new_states
        self._zero_states = new_zstates
        self._zero_residuals = new_zres
        self.optimizer.num_update = self._t
        self._record_comm()
        return NDArray(loss)

    def _record_comm(self):
        """Per-step comm accounting (profiler.get_comm_stats): analytic
        per-device ring bytes — reduce-scatter + all-gather legs on the ZeRO
        path, the full-allreduce equivalent on the replicated path — so the
        two paths are directly comparable."""
        from .. import profiler
        n = data_size(self.mesh)
        if self.zero and self._zero_layout is not None:
            c = self._zero_layout.step_comm()
            if self.stage >= 2 and self.micro_batches > 1:
                # ZeRO-2 reduce-scatters each micro-gradient into the shard
                # accumulator: k reduce legs per step instead of one
                c["bytes_reduced"] *= self.micro_batches
            if self.stage >= 3 and self._param_sh is not None:
                # stage-3 params live 1/N: the compiler's JIT all-gathers
                # (fwd + bwd) and grad reduce-scatter don't pass through the
                # explicit bucket collectives, so account them analytically
                # with the same per-device ring fractions step_comm() uses
                axis = fsdp_axis_name(self.mesh)
                nf = fsdp_size(self.mesh)
                fsdp_bytes = sum(
                    int(np.prod(p.data().shape))
                    * np.dtype(str(p.data().dtype)).itemsize
                    for p, sh in zip(self._param_handles, self._param_sh)
                    if any(fsdp_mod._mentions(e, axis) for e in sh.spec))
                frac = (nf - 1) / nf if nf > 1 else 0.0
                c["bytes_gathered"] += int(2 * fsdp_bytes * frac)
                c["bytes_reduced"] += int(fsdp_bytes * frac)
            profiler.record_comm_step(zero=True, allreduce_bytes=0, **c)
        else:
            frac = 2.0 * (n - 1) / n if n > 1 else 0.0
            grad_bytes = sum(
                int(np.prod(p.data().shape))
                * np.dtype(str(p.data().dtype)).itemsize
                for p in self._param_handles)
            profiler.record_comm_step(dp=n,
                                      allreduce_bytes=int(grad_bytes * frac))

    def optimizer_state_bytes(self) -> int:
        """Optimizer-slot bytes RESIDENT PER DEVICE (the ZeRO-1 headline
        metric: 1/N with sharding on, full with it off). Valid after the
        first step."""
        def per_device(arr):
            sh = getattr(arr, "sharding", None)
            shape = tuple(arr.shape)
            if sh is not None and hasattr(sh, "shard_shape"):
                shape = sh.shard_shape(shape)
            return int(np.prod(shape)) * np.dtype(str(arr.dtype)).itemsize \
                if len(shape) else np.dtype(str(arr.dtype)).itemsize
        total = 0
        for st in list(self._states) + list(self._zero_states):
            for s in (st or ()):
                if hasattr(s, "dtype"):
                    total += per_device(s)
        for r in self._zero_residuals:
            if r is not None:
                total += per_device(r)
        return total

    def step(self, x, y) -> float:
        return float(self.step_async(x, y).data)

    def device_feed(self, batches, depth: Optional[int] = None):
        """Wrap an iterable of ``(x, y)`` batches (or ``DataBatch``es) in a
        ``device_feed.DeviceFeed`` committed to this trainer's dp batch
        sharding: a producer thread keeps the next ``depth`` batches resident
        across the mesh, and ``step_async``'s ``shard_batch`` recognizes them
        as placed (no second ``device_put``). Multi-process: each rank feeds
        its LOCAL shard, exactly like ``shard_batch``. ::

            for x, y in dpt.device_feed(loader):
                dpt.step_async(x, y)
        """
        from ..device_feed import DeviceFeed
        return DeviceFeed(batches, depth=depth, placement=self.mesh)

    def cost_analysis(self) -> dict:
        """XLA's own cost model for the compiled step (flops, bytes accessed).
        Valid after the first step; used by bench.py for honest MFU accounting.
        The lowering/compile for the analysis is cached (first call only)."""
        if self._step_fn is None or not hasattr(self, "_last_avals"):
            raise RuntimeError("run at least one step first")
        if not hasattr(self, "_cost_cache"):
            compiled = self._step_fn.lower(*self._last_avals).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            self._cost_cache = dict(ca) if ca else {}
        return self._cost_cache
