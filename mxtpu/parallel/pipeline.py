"""Pipeline parallelism over a ``pp`` mesh axis — the TPU-native PP hook
(SURVEY.md §2.3: the reference has no pipeline parallelism; TP/PP/SP hooks are
mandated because pjit meshes make them cheap).

GPipe-style schedule expressed as ONE ``shard_map``-ed ``lax.scan``: every
device holds one stage's parameters (stacked pytree sharded over ``pp``);
each scan step, activations hop one stage forward over ICI via ``ppermute``
while a new microbatch enters stage 0 — the classic pipelined loop, compiled
into a single XLA program. Differentiable end-to-end (jax autodiff through
``ppermute`` reverses the ring), so the same function serves training.

Bubble fraction is the usual (S-1)/(M+S-1) for S stages / M microbatches.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, get_default_mesh

__all__ = ["gpipe"]


def gpipe(stage_fn: Callable, stacked_params, x, mesh: Optional[Mesh] = None,
          axis_name: str = "pp", batch_spec: Optional[P] = None):
    """Run ``x`` through S pipelined stages.

    ``stage_fn(params_i, h) -> h`` applies one stage. ``stacked_params`` is a
    pytree whose leaves are stacked along a leading S axis (stage i's slice
    lives on pp-rank i). ``x``: (M, B, ...) microbatches with M >= 1; the
    activation shape must be constant across stages (uniform-width pipeline —
    standard for transformer blocks). Returns (M, B, ...) outputs.

    ``batch_spec`` composes pp with the mesh's OTHER axes: the spec of one
    microbatch (B, ...) — e.g. ``P(("dp", "fsdp"))`` to shard B over the data
    axes while the pp ring permutes over its own axis. Stream and output
    carry the spec shifted one dim right (the leading M axis stays
    unsharded); default keeps the old fully-replicated behavior.
    """
    mesh = mesh or get_default_mesh()
    S = mesh.shape[axis_name]
    M = x.shape[0]
    n_steps = M + S - 1

    # pad the microbatch stream with S-1 dummy slots that flush the pipeline
    pad = jnp.zeros((S - 1,) + x.shape[1:], x.dtype)
    stream = jnp.concatenate([x, pad], axis=0)          # (n_steps, B, ...)

    def spmd(params_stacked, stream_loc):
        # params_stacked: (1, ...) — this device's stage slice
        my_params = jax.tree.map(lambda p: p[0], params_stacked)
        idx = lax.axis_index(axis_name)

        def step(carry, x_t):
            h_in = carry                                 # activation entering my stage
            # stage 0 consumes the incoming microbatch; others their buffer
            h = jnp.where(idx == 0, x_t, h_in)
            h_out = stage_fn(my_params, h)
            # the finished output of the LAST stage, broadcast to every rank
            # (masked psum) so the scan output is pp-replicated
            y_t = lax.psum(jnp.where(idx == S - 1, h_out,
                                     jnp.zeros_like(h_out)), axis_name)
            # hop one stage forward over the ICI ring
            shifted = lax.ppermute(h_out, axis_name,
                                   [(i, (i + 1) % S) for i in range(S)])
            return shifted, y_t

        carry0 = jnp.zeros_like(stream_loc[0])
        try:  # newer jax: carries that become device-varying must start varied
            carry0 = lax.pvary(carry0, axis_name)
        except AttributeError:
            pass
        _, ys = lax.scan(step, carry0, stream_loc)
        return ys                                        # (n_steps, B, ...)

    params_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)
    # stream/output spec: microbatch spec shifted right of the leading M axis
    stream_spec = P(None, *batch_spec) if batch_spec is not None else P()
    from .collectives import shard_map_compat
    fn = shard_map_compat(spmd, mesh,
                          (params_spec, stream_spec),
                          stream_spec)
    ys = fn(stacked_params, stream)
    # outputs for microbatch m exit the last stage at step m + S - 1 and are
    # visible (after the rotation) on every rank at that step
    return ys[S - 1:]
