"""FSDP / ZeRO-3 staging — full parameter sharding on a named ``fsdp`` axis.

ZeRO stages (Rajbhandari et al., 2020) map onto mxtpu as:

- **Stage 1** — optimizer slots live 1/N per device inside flat buckets
  (``zero.ZeroLayout``); params and grads stay replicated.
- **Stage 2** — gradients are additionally held reduce-scattered 1/N per
  bucket: micro-batch accumulators allocate the packed bucket *shard*, never
  the replicated grad, so accumulation memory also drops 1/N.
- **Stage 3 / FSDP** — parameters are *resident* 1/N, each sharded on its
  first eligible dimension over the ``fsdp`` mesh axis. The compiled step
  takes sharded params in and XLA inserts the just-in-time per-layer
  all-gathers in forward/backward (and reduce-scatters the grads back to the
  shards), overlapping them against the matmuls — the GSPMD formulation of
  FSDP. Optimizer slots follow the param's sharding, so state is 1/N without
  bucketing for every fsdp-sharded param.

The stage knob is ``MXTPU_ZERO_STAGE=1|2|3`` (default 1, bit-parity with
PR 4 behavior). On meshes without an axis literally named ``fsdp`` the last
data axis doubles as the parameter-shard axis, so a plain ``("dp",)`` mesh
gives classic single-level FSDP at stage 3 and ``("dp", "fsdp", "tp")``
gives HSDP composed with tensor parallelism.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import data_axis_names, data_size, fsdp_axis_name, fsdp_size

__all__ = ["zero_stage", "compose_spec", "fsdp_param_specs",
           "per_device_bytes", "replicated_bytes", "measure_memory",
           "SpecLayout", "parameter_spec_from_name", "scale_spec",
           "filter_spec", "layout_scope", "current_layout"]


def zero_stage() -> int:
    """The active ZeRO stage from ``MXTPU_ZERO_STAGE`` (default 1, clamped
    to [1, 3]). Read at trainer/executor construction so benchmarks can flip
    it per scenario."""
    try:
        stage = int(os.environ.get("MXTPU_ZERO_STAGE", "1"))
    except ValueError:
        stage = 1
    return max(1, min(3, stage))


def _spec_entries(spec: Optional[P], ndim: int) -> List:
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return entries[:ndim]


def _mentions(entry, axis: str) -> bool:
    if entry is None:
        return False
    if isinstance(entry, (tuple, list)):
        return axis in entry
    return entry == axis


def compose_spec(shape: Sequence[int], base_spec: Optional[P],
                 mesh: Mesh) -> Optional[P]:
    """Insert the fsdp axis into ``base_spec`` (the param's tp layout) on
    dimension 0 when it is unsharded and divisible by the fsdp degree — the
    SpecLayout data/fsdp/tp composition. Returns the composed spec, or None
    when dim 0 is ineligible (such params stay replicated and take the
    bucketed stage-1 treatment instead).

    Only dim 0 is considered on purpose: sharding a contraction dimension
    makes XLA compute the forward matmul as per-device partial sums + psum,
    which changes the floating-point reduction order and breaks bit-parity
    with stages 1/2. Dim-0 (output-dim) sharding only moves where the
    all-gather happens, never the arithmetic order."""
    axis = fsdp_axis_name(mesh)
    n = fsdp_size(mesh)
    if n <= 1 or not shape:
        return None
    entries = _spec_entries(base_spec, len(shape))
    if any(_mentions(e, axis) for e in entries):
        return base_spec  # already fsdp-sharded
    if entries[0] is None and shape[0] % n == 0 and shape[0] >= n:
        entries[0] = axis
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)
    return None


def fsdp_param_specs(shapes: Sequence[Sequence[int]],
                     base_specs: Sequence[Optional[P]],
                     mesh: Mesh) -> List[Optional[P]]:
    """Composed per-param specs for stage 3; None marks bucket-eligible
    (replicated-resident) params."""
    return [compose_spec(s, b, mesh) for s, b in zip(shapes, base_specs)]


# ---------------------------------------------------------------------------
# SpecLayout — the canonical per-parameter / per-activation layout table
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SpecLayout:
    """THE per-parameter/per-activation partition-spec table for the composed
    dp×fsdp×tp(×pp) flagship — one frozen source of truth instead of ad-hoc
    spec dicts scattered per entry point (SNIPPETS [3] pattern: embeddings on
    fsdp×tp, activations on data×tp).

    Weight specs follow the gluon ``Dense`` convention ``(out_features,
    in_features)`` — dim 0 is the OUTPUT dimension. Column-parallel layers
    (qkv, ffn-up) therefore shard dim 0 on ``tp``; row-parallel layers
    (attn-out, ffn-down) shard dim 1. The fsdp residency axis is NOT in the
    base table: ``compose_spec`` inserts it on free, divisible dim 0s at
    ZeRO stage 3 (dim-0-only on purpose — see its docstring on reduction
    order), so the same table serves every stage.

    ``ulysses_axis`` names the mesh axis the attention spec-flip exchanges
    sequence for heads over (DeepSpeed-Ulysses); the flagship reuses ``tp``
    — heads are tp-sharded anyway, so the flip is a pure GSPMD reshard that
    lowers to the native all-to-all (the jit-reshard fast path).
    """
    data_axes: Tuple[str, ...] = ("dp", "fsdp")
    fsdp_axis: str = "fsdp"
    tp_axis: str = "tp"
    ulysses_axis: str = "tp"

    # -- parameter table (gluon (out, in) weight convention) ----------------
    def embeddings(self) -> P:
        # (vocab, units): vocab is both the lookup dim and the tied-head
        # OUTPUT dim — sharding it over fsdp×tp never touches a contraction
        return P((self.fsdp_axis, self.tp_axis))

    def qkv_projection(self) -> P:
        return P(self.tp_axis)            # head-parallel columns

    def attn_out(self) -> P:
        return P(None, self.tp_axis)      # row-parallel (Megatron pair)

    def ffn_up(self) -> P:
        return P(self.tp_axis)

    def ffn_down(self) -> P:
        return P(None, self.tp_axis)

    def vector(self) -> P:
        return P()                        # norms, biases, pos-embed

    # -- activation table ---------------------------------------------------
    def activations(self) -> P:
        """(B, T, C) between layers: batch over every data axis."""
        return P(self.data_axes)

    def seq_activations(self) -> P:
        """(B, T, C) in Ulysses regions: sequence additionally sharded."""
        return P(self.data_axes, self.ulysses_axis)

    def head_activations(self) -> P:
        """(B, H, T, D) inside attention: heads sharded, FULL sequence per
        device group — the post-all-to-all Ulysses layout."""
        return P(self.data_axes, self.ulysses_axis)

    def kv_cache(self) -> P:
        """(L, 2, S, H, TOT, D) paged serving KV cache (and its rank-5
        QuantKV scale): heads on tp. The serving-engine layout
        (``mxtpu.serving.sharded.ServingLayout``) overrides this to also
        shard slots over fsdp."""
        return P(None, None, None, self.tp_axis)


def audit_spec_table(layout: Optional[SpecLayout] = None,
                     units: int = 64, vocab: int = 64, ffn: int = 256,
                     layers: int = 2, heads: int = 2, slots: int = 4,
                     tot: int = 64, head_dim: int = 32):
    """``(role, probe shape, spec)`` rows over the canonical tiny-model
    geometry — what the program auditor (``--audit``) shardchecks.  Kept
    next to :class:`SpecLayout` so a new table entry is audited the moment
    it is added: every axis a spec names must exist on the audit mesh
    (A101) and every sharded probe dim must divide cleanly (A102) — a
    table change that silently degrades to replicated via
    :func:`filter_spec` shows up here instead of as a perf mystery.
    Shapes follow the gluon ``(out, in)`` weight convention; the kv row is
    the serving ``(L, 2, S, TOT? H, ...)`` page geometry."""
    layout = layout or SpecLayout()
    return [
        ("embeddings", (vocab, units), layout.embeddings()),
        ("qkv_projection", (units, units), layout.qkv_projection()),
        ("attn_out", (units, units), layout.attn_out()),
        ("ffn_up", (ffn, units), layout.ffn_up()),
        ("ffn_down", (units, ffn), layout.ffn_down()),
        ("vector", (units,), layout.vector()),
        ("kv_cache", (layers, 2, slots, heads, tot, head_dim),
         layout.kv_cache()),
    ]


def scale_spec(weight_spec: Optional[P]) -> P:
    """Partition spec for a per-row quantization scale vector riding a 2-D
    ``(out, in)`` weight (``mxtpu.quant``): the scale has one entry per OUTPUT
    row, so it shards exactly like the weight's dim 0 and nothing else —
    column-parallel weights get tp-sharded scales, row-parallel weights get
    replicated scales (their dim-0 is unsharded)."""
    if weight_spec is None:
        return P()
    entries = tuple(weight_spec)
    return P(entries[0]) if entries and entries[0] is not None else P()


def parameter_spec_from_name(name: str, layout: Optional[SpecLayout] = None) -> P:
    """Map a gluon parameter name onto the SpecLayout table (the model-zoo
    naming heuristic: ``multiheadattention*_dense0..2`` are q/k/v, ``dense3``
    the output projection; a block's own ``dense0/dense1`` are the FFN pair;
    ``embedding*_weight`` is the tied table)."""
    layout = layout or SpecLayout()
    n = name.lower()
    if "embedding" in n and n.endswith("weight"):
        return layout.embeddings()
    if "multiheadattention" in n:
        if n.endswith("dense3_weight"):
            return layout.attn_out()
        if n.endswith("weight"):
            return layout.qkv_projection()
        return layout.vector()
    if n.endswith("dense0_weight"):
        return layout.ffn_up()
    if n.endswith("dense1_weight"):
        return layout.ffn_down()
    return layout.vector()


def filter_spec(spec: Optional[P], shape: Sequence[int], mesh: Mesh) -> P:
    """Project a table spec onto what THIS mesh/shape supports: axis names
    the mesh doesn't carry are dropped, and a dim whose sharded degree does
    not divide it falls back to replicated — so one table serves the 8-way
    composed mesh and a single-device smoke run alike."""
    entries = _spec_entries(spec, len(shape))
    out: List = []
    for dim, e in zip(shape, entries):
        names = list(e) if isinstance(e, (tuple, list)) else ([e] if e else [])
        names = [a for a in names if a in mesh.axis_names]
        degree = 1
        for a in names:
            degree *= int(mesh.shape[a])
        if not names or degree <= 1 or dim % degree != 0:
            out.append(None)
        else:
            out.append(tuple(names) if len(names) > 1 else names[0])
    while out and out[-1] is None:
        out.pop()
    return P(*out)


# -- activation layout scope -------------------------------------------------
# Model code (MultiHeadAttention, TransformerLM) consults this scope to place
# with_sharding_constraint spec flips while a composed-mesh step traces; no
# scope -> zero overhead, models stay mesh-agnostic.

_layout_scope = threading.local()


def current_layout():
    """The active ``(layout, mesh)`` pair, or None outside a scope."""
    return getattr(_layout_scope, "value", None)


@contextmanager
def layout_scope(layout: SpecLayout, mesh: Mesh):
    """Activate the SpecLayout for model-side activation constraints. Enter
    around trainer construction + steps (the constraint only fires on
    tracers, so eager predicts under an open scope stay untouched)."""
    prev = getattr(_layout_scope, "value", None)
    _layout_scope.value = (layout, mesh)
    try:
        yield
    finally:
        _layout_scope.value = prev


def constrain(raw, entry: str):
    """Apply the active scope's ``entry`` activation spec (a SpecLayout
    method name, e.g. ``"seq_activations"``) to a raw jax value via
    ``with_sharding_constraint`` — but ONLY while a composed-mesh step is
    tracing (value is a Tracer under an open scope). Everywhere else this is
    the identity, so model code can call it unconditionally. The spec is
    mesh/shape-filtered, and a constraint that filters down to fully
    replicated is skipped (GSPMD would otherwise force a gather)."""
    scope = current_layout()
    if scope is None:
        return raw
    import jax
    if not isinstance(raw, jax.core.Tracer):
        return raw
    layout, mesh = scope
    spec = filter_spec(getattr(layout, entry)(), raw.shape, mesh)
    if spec == P():
        return raw
    return jax.lax.with_sharding_constraint(raw, NamedSharding(mesh, spec))


def per_device_bytes(arr) -> int:
    """Resident bytes of one array on ONE device, honoring its sharding."""
    size = int(np.prod(arr.shape)) if arr.shape else 1
    itemsize = np.dtype(arr.dtype).itemsize
    sh = getattr(arr, "sharding", None)
    if sh is not None and hasattr(sh, "shard_shape"):
        try:
            shp = sh.shard_shape(tuple(arr.shape))
            size = int(np.prod(shp)) if shp else 1
        except Exception:
            pass
    return size * itemsize


def replicated_bytes(arr) -> int:
    size = int(np.prod(arr.shape)) if arr.shape else 1
    return size * np.dtype(arr.dtype).itemsize


def measure_memory(stage: int, mesh: Optional[Mesh], params: Sequence,
                   slot_arrays: Sequence, grad_bytes_full: int,
                   record: bool = True) -> dict:
    """Per-device resident byte accounting for params/grads/slots, plus the
    replicated-equivalent figures the shrink ratio is quoted against.

    ``params``/``slot_arrays`` are jax arrays (placed, so their shardings are
    the ground truth). Gradients are transient in the fused program; they are
    accounted analytically: full size at stage 1, 1/N (data degree) at
    stages 2/3 where they are held packed/reduce-scattered."""
    n_data = data_size(mesh) if mesh is not None else 1
    param_dev = sum(per_device_bytes(p) for p in params)
    param_repl = sum(replicated_bytes(p) for p in params)
    slot_dev = sum(per_device_bytes(s) for s in slot_arrays)
    slot_repl = sum(replicated_bytes(s) for s in slot_arrays)
    grad_dev = grad_bytes_full if stage < 2 else -(-grad_bytes_full // max(1, n_data))
    stats = {
        "stage": int(stage),
        "data_degree": int(n_data),
        "fsdp_degree": int(fsdp_size(mesh)) if mesh is not None else 1,
        "param_bytes_per_device": int(param_dev),
        "grad_bytes_per_device": int(grad_dev),
        "slot_bytes_per_device": int(slot_dev),
        "replicated_param_bytes": int(param_repl),
        "replicated_grad_bytes": int(grad_bytes_full),
        "replicated_slot_bytes": int(slot_repl),
    }
    if record:
        from ..observability import metrics
        metrics.record_memory_stats(**stats)
    return stats
