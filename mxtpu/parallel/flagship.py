"""The composed flagship — dp×fsdp×tp(×pp) TransformerLM training on ONE mesh
(ROADMAP item 1: every parallelism axis the framework grew separately — ZeRO-3
residency, Megatron tp pairs, Ulysses sequence exchange, GPipe stages —
composed into a single SPMD step).

Composition recipe:

* **mesh** — ``("dp", "fsdp", "tp")`` (+ ``"pp"`` for the pipelined forward):
  batch shards over dp×fsdp (``mesh.data_axis_names``), stage-3 params are
  resident 1/fsdp on free dim 0s, Megatron pairs shard over tp.
* **specs** — ONE :class:`~mxtpu.parallel.fsdp.SpecLayout` table is the
  canonical source; :func:`flagship_param_shardings` projects it onto the
  model's parameter names/shapes, and the model-side activation constraints
  (``layout_scope``) flip sequence↔head sharding around attention so GSPMD
  emits the native all-to-all — the same jit-reshard fast path
  ``collectives.all_to_all_array`` defaults to.
* **step** — the stock :class:`~mxtpu.parallel.data_parallel.DataParallelTrainer`
  whole-step jit; nothing flagship-specific compiles. Trace-once is asserted
  off ``step_cache.cache_stats("data_parallel_step")``.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import fsdp as fsdp_mod
from .data_parallel import DataParallelTrainer
from .mesh import Mesh, get_default_mesh, make_mesh
from .pipeline import gpipe

__all__ = ["flagship_mesh", "flagship_param_shardings", "train_flagship",
           "flagship_pp_forward"]


def flagship_mesh(dp: int = 2, fsdp: int = 2, tp: int = 2, pp: int = 1,
                  devices=None) -> Mesh:
    """The composed mesh, axes in ICI-locality order: tp (and pp) innermost
    so the chattiest collectives ride neighbor links; singleton axes are kept
    — GSPMD treats them as replicated and the SAME program text serves every
    decomposition of the device count."""
    shape = (dp, fsdp, tp) + ((pp,) if pp > 1 else ())
    names = ("dp", "fsdp", "tp") + (("pp",) if pp > 1 else ())
    return make_mesh(shape, names, devices)


def flagship_param_shardings(block, layout: Optional[fsdp_mod.SpecLayout],
                             mesh: Mesh) -> Callable[[str], P]:
    """Project the SpecLayout table onto ``block``'s parameters: a
    ``name -> PartitionSpec`` callable for ``DataParallelTrainer``, with each
    table spec filtered by the mesh's axes and the param's divisibility
    (so the same table drives the 8-way mesh and the 1-device reference)."""
    layout = layout or fsdp_mod.SpecLayout()
    shapes = {name: p.shape for name, p in block.collect_params().items()
              if p.shape is not None}

    def spec_for(name: str) -> P:
        base = fsdp_mod.parameter_spec_from_name(name, layout)
        shape = shapes.get(name)
        if shape is None:
            return P()
        return fsdp_mod.filter_spec(base, shape, mesh)

    return spec_for


def _lm_batches(vocab: int, batch: int, seq: int, n_batches: int, seed: int):
    """Deterministic synthetic LM stream (markov-ish so loss actually drops):
    next token = (token * 3 + noise) mod vocab."""
    rs = np.random.RandomState(seed)
    xs, ys = [], []
    for _ in range(n_batches):
        t0 = rs.randint(0, vocab, size=(batch, 1))
        toks = [t0]
        for _ in range(seq):
            nxt = (toks[-1] * 3 + (rs.rand(batch, 1) < 0.1)) % vocab
            toks.append(nxt.astype(np.int64))
        seqs = np.concatenate(toks, axis=1)
        xs.append(seqs[:, :seq].astype(np.int32))
        ys.append(seqs[:, 1:seq + 1].astype(np.int32))
    return xs, ys


def train_flagship(mesh: Optional[Mesh] = None, *, vocab: int = 64,
                   units: int = 64, num_layers: int = 2, num_heads: int = 2,
                   batch: int = 16, seq: int = 32, epochs: int = 3,
                   batches_per_epoch: int = 4, lr: float = 0.1,
                   seed: int = 0, layout: Optional[fsdp_mod.SpecLayout] = None,
                   zero_stage: Optional[int] = 3) -> dict:
    """Fit a tiny TransformerLM on the composed mesh; returns per-epoch mean
    losses plus the compile/memory evidence the guard asserts on.

    The SAME function run on a 1-device mesh is the equivalence reference:
    identical seed → identical init and batch stream, so per-epoch losses
    must agree to sharded-reduction tolerance.
    """
    import os
    import mxtpu as mx
    from mxtpu import gluon, optimizer as opt_mod
    from mxtpu.gluon.model_zoo.transformer import TransformerLM
    from ..step_cache import cache_stats

    mesh = mesh or get_default_mesh()
    layout = layout or fsdp_mod.SpecLayout()
    saved_stage = os.environ.get("MXTPU_ZERO_STAGE")
    if zero_stage is not None:
        os.environ["MXTPU_ZERO_STAGE"] = str(zero_stage)
    try:
        mx.rng.seed(seed)
        net = TransformerLM(vocab, units=units, num_layers=num_layers,
                            num_heads=num_heads, max_len=seq)
        net.initialize(init=mx.initializer.Xavier())
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = DataParallelTrainer(
            net, loss_fn, opt_mod.SGD(learning_rate=lr), mesh,
            param_shardings=flagship_param_shardings(net, layout, mesh))
        stats = cache_stats("data_parallel_step")
        traces0 = stats.traces
        xs, ys = _lm_batches(vocab, batch, seq, batches_per_epoch, seed)
        losses = []
        with fsdp_mod.layout_scope(layout, mesh):
            for _ in range(epochs):
                ep = [float(trainer.step(mx.nd.array(x), mx.nd.array(y)))
                      for x, y in zip(xs, ys)]
                losses.append(float(np.mean(ep)))
        from mxtpu import profiler
        return {
            "losses": losses,
            "traces": stats.traces - traces0,
            "mesh_axes": dict(mesh.shape),
            "stage": trainer.stage,
            "memory": profiler.get_memory_stats(),
            "params": {n: tuple(getattr(p.data().data.sharding, "spec", P()))
                       for n, p in net.collect_params().items()
                       if p.shape is not None},
        }
    finally:
        if saved_stage is None:
            os.environ.pop("MXTPU_ZERO_STAGE", None)
        else:
            os.environ["MXTPU_ZERO_STAGE"] = saved_stage


def flagship_pp_forward(mesh: Optional[Mesh] = None, *, units: int = 32,
                        num_heads: int = 2, micro: int = 4, batch: int = 4,
                        seq: int = 16, seed: int = 0) -> dict:
    """The ×pp leg: one stacked TransformerBlock per pp stage run through
    ``gpipe`` with the batch sharded over the data axes (``batch_spec``
    composition), checked against the sequential stage-by-stage forward.
    Returns max |Δ| so callers can assert agreement."""
    mesh = mesh or get_default_mesh()
    S = int(mesh.shape["pp"])
    rs = np.random.RandomState(seed)
    D = units // num_heads

    def stage_fn(params, h):
        # pre-LN block in raw jax (mirrors TransformerBlock.forward /
        # serving_step layer math)
        def ln(x, g, b):
            mu = jnp.mean(x, axis=-1, keepdims=True)
            var = jnp.var(x, axis=-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-5) * g + b

        x = h
        hn = ln(x, params["ln1_g"], params["ln1_b"])
        B, T, C = hn.shape
        q = (hn @ params["wq"].T).reshape(B, T, num_heads, D).transpose(0, 2, 1, 3)
        k = (hn @ params["wk"].T).reshape(B, T, num_heads, D).transpose(0, 2, 1, 3)
        v = (hn @ params["wv"].T).reshape(B, T, num_heads, D).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s, -1e30)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhts,bhsd->bhtd", a, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, C) @ params["wo"].T
        x = x + o
        g = ln(x, params["ln2_g"], params["ln2_b"])
        f = jax.nn.gelu(g @ params["w1"].T, approximate=True) @ params["w2"].T
        return x + f

    def init_stage():
        s = 1.0 / np.sqrt(units)
        return {
            "ln1_g": np.ones(units, np.float32),
            "ln1_b": np.zeros(units, np.float32),
            "ln2_g": np.ones(units, np.float32),
            "ln2_b": np.zeros(units, np.float32),
            "wq": (rs.randn(units, units) * s).astype(np.float32),
            "wk": (rs.randn(units, units) * s).astype(np.float32),
            "wv": (rs.randn(units, units) * s).astype(np.float32),
            "wo": (rs.randn(units, units) * s).astype(np.float32),
            "w1": (rs.randn(4 * units, units) * s).astype(np.float32),
            "w2": (rs.randn(units, 4 * units) * s).astype(np.float32),
        }

    stages = [init_stage() for _ in range(S)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *stages)
    x = jnp.asarray(rs.randn(micro, batch, seq, units).astype(np.float32))

    data_axes = tuple(a for a in mesh.axis_names if a in ("dp", "fsdp"))
    batch_spec = P(data_axes) if data_axes else None
    ys = gpipe(stage_fn, stacked, x, mesh, axis_name="pp",
               batch_spec=batch_spec)

    ref = x
    for p in stages:
        ref = jax.vmap(lambda h, p=p: stage_fn(p, h))(ref)
    err = float(jnp.max(jnp.abs(ys - ref)))
    return {"max_err": err, "stages": S, "micro": micro,
            "batch_spec": tuple(batch_spec) if batch_spec else ()}
