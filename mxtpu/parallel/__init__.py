"""TPU-first parallelism: meshes, collectives, sharded data-parallel training.

This package is the re-imagining of the reference's distributed stack (SURVEY.md §2.3):
Comm/NCCL/ps-lite → XLA collectives over ICI/DCN; DataParallelExecutorGroup → sharded
SPMD steps; ``ctx_group`` model parallelism → pjit shardings. Long-context sequence
parallelism lives in ``ring_attention`` (K/V rotation, O(T/n) memory) and
``ulysses`` (all-to-all head/sequence reshuffle, 2 collectives).
"""

from . import collectives
from . import mesh
from .collectives import (all_gather, all_to_all, all_to_all_array,
                          allgather_array, allreduce, allreduce_array,
                          allreduce_processes, barrier, broadcast_array,
                          broadcast_processes, pmean, ppermute,
                          process_barrier, psum, reduce_scatter,
                          reduce_scatter_array)
from .data_parallel import DataParallelTrainer, place, replicate, shard_batch
from .mesh import (Mesh, NamedSharding, P, data_axis_names,
                   data_parallel_mesh, data_size, dp_axis_name, dp_size,
                   force_virtual_cpu_devices, fsdp_axis_name, fsdp_size,
                   get_default_mesh, make_mesh, set_default_mesh)
from . import zero
from .zero import ZeroLayout, zero_bucket_bytes, zero_enabled
from . import fsdp
from .fsdp import (SpecLayout, compose_spec, filter_spec, fsdp_param_specs,
                   layout_scope, parameter_spec_from_name, zero_stage)
from . import ring_attention
from .ring_attention import ring_attention_inner, ring_self_attention
from . import ulysses
from .ulysses import ulysses_attention_inner, ulysses_self_attention
from . import pipeline
from .pipeline import gpipe
from . import moe
from .moe import expert_parallel_ffn
from . import flagship
from .flagship import (flagship_mesh, flagship_param_shardings,
                       flagship_pp_forward, train_flagship)
