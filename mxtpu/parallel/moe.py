"""Expert parallelism over an ``ep`` mesh axis — the EP hook (task mandate:
real tp/pp/dp/sp/ep shardings; the reference predates MoE entirely).

Top-1-routed mixture-of-experts FFN in the canonical TPU formulation: tokens
are ep-sharded, each device owns exactly one expert's weights, and dispatch/
return ride ``lax.all_to_all`` over ICI — the same program structure as
GShard/Switch. Capacity-bounded: each expert accepts at most ``capacity``
tokens per source device; overflow tokens pass through with a zero expert
contribution (standard capacity-drop semantics).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import all_to_all_array, shard_map_compat
from .mesh import Mesh, get_default_mesh

__all__ = ["expert_parallel_ffn"]


def expert_parallel_ffn(router_w, w1, w2, x, mesh: Optional[Mesh] = None,
                        axis_name: str = "ep",
                        capacity_factor: float = 1.0):
    """MoE FFN: ``y[t] = gate[t] * FFN_{e(t)}(x[t])`` with expert-sharded
    weights (one expert per ep rank).

    ``router_w``: (d, E) routing matrix (replicated). ``w1``: (E, d, h),
    ``w2``: (E, h, d) expert weights, stacked over the leading expert axis and
    sharded over ``ep`` (one expert per ep rank: E == ep size). ``x``: (N, d)
    tokens, N divisible by E. Returns (N, d).
    """
    mesh = mesh or get_default_mesh()
    E = mesh.shape[axis_name]
    N, d = x.shape
    if router_w.shape[1] != E or w1.shape[0] != E or w2.shape[0] != E:
        raise ValueError(
            f"expert count mismatch: ep axis has {E} ranks but router_w/w1/w2 "
            f"carry {router_w.shape[1]}/{w1.shape[0]}/{w2.shape[0]} experts "
            "(one expert per ep rank)")
    if N % E != 0:
        raise ValueError(f"token count {N} not divisible by ep size {E}")
    n_loc = N // E
    capacity = max(1, int(capacity_factor * n_loc))

    def spmd(router_w, w1_loc, w2_loc, x_loc):
        # x_loc: (n_loc, d); w1_loc/w2_loc: (1, d, h)/(1, h, d) — my expert
        logits = x_loc @ router_w                        # (n_loc, E)
        expert = jnp.argmax(logits, axis=-1)             # (n_loc,)
        gate = jax.nn.softmax(logits, axis=-1)[
            jnp.arange(n_loc), expert]                   # (n_loc,)

        # position of each token within its expert's send buffer
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)   # (n_loc, E)
        pos = jnp.cumsum(onehot, axis=0) * onehot        # 1-based where routed
        pos = jnp.sum(pos, axis=-1) - 1                  # (n_loc,)
        keep = pos < capacity

        send = jnp.zeros((E, capacity, d), x_loc.dtype)
        send = send.at[expert, jnp.where(keep, pos, 0)].add(
            jnp.where(keep[:, None], x_loc, 0.0))

        # exchange: device e receives every device's buffer for expert e
        recv = all_to_all_array(send, axis_name=axis_name, split_axis=0,
                                concat_axis=0, tiled=False)  # (E_src, capacity, d)

        h = recv.reshape(-1, d) @ w1_loc[0]              # my expert's FFN
        h = jax.nn.relu(h)
        out = (h @ w2_loc[0]).reshape(E, capacity, d)

        # return trip + gather each token's result back by its position
        back = all_to_all_array(out, axis_name=axis_name, split_axis=0,
                                concat_axis=0, tiled=False)  # (E_expert, capacity, d)
        y = back[expert, jnp.where(keep, pos, 0)]
        y = jnp.where(keep[:, None], y * gate[:, None], 0.0)
        return y

    fn = shard_map_compat(
        spmd, mesh,
        (P(), P(axis_name), P(axis_name), P(axis_name)),
        P(axis_name))
    return fn(router_w, w1, w2, x)
