"""Device meshes — the TPU-native device model (SURVEY.md §2.3 TPU-equivalents).

The reference enumerates GPUs into flat context lists; here parallelism is a named-axis
mesh (``jax.sharding.Mesh``) over which pjit shardings and shard_map collectives are
expressed. Standard axis names: ``dp`` (data), ``tp`` (tensor), ``pp`` (pipeline),
``sp`` (sequence/context). ICI topology is honored by device order (jax returns
devices in torus order, so contiguous mesh axes ride ICI neighbors).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["Mesh", "NamedSharding", "P", "force_virtual_cpu_devices",
           "make_mesh", "data_parallel_mesh", "dp_axis_name", "dp_size",
           "data_axis_names", "data_size", "fsdp_axis_name", "fsdp_size",
           "get_default_mesh", "set_default_mesh"]

_default_mesh: Optional[Mesh] = None


def make_mesh(shape: Sequence[int] = None, axis_names: Sequence[str] = ("dp",),
              devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (devices.size,)
    need = int(np.prod(shape))
    if need > devices.size:
        raise ValueError(f"mesh {tuple(shape)} needs {need} devices, have {devices.size}")
    return Mesh(devices[:need].reshape(tuple(shape)), tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = num_devices or len(devs)
    return make_mesh((n,), ("dp",), devs[:n])


def dp_axis_name(mesh: Mesh) -> str:
    """The data-parallel axis by convention: the mesh's FIRST named axis
    (batches shard over it; ZeRO-1 shards gradients/optimizer state over it)."""
    return mesh.axis_names[0]


def dp_size(mesh: Mesh) -> int:
    """Degree of the data-parallel axis — the N in ZeRO's 1/N state shards."""
    return int(mesh.shape[mesh.axis_names[0]])


def data_axis_names(mesh: Mesh) -> Tuple[str, ...]:
    """The axes the BATCH shards over: every ``dp``/``fsdp`` axis present.

    An HSDP mesh ``("dp", "fsdp", "tp")`` feeds batches sharded over
    ``("dp", "fsdp")`` — replicas × shards both consume distinct data — while
    ``tp`` sees the batch replicated. Meshes with neither conventional name
    keep the first-axis-is-data convention (``dp_axis_name``)."""
    named = tuple(a for a in mesh.axis_names if a in ("dp", "fsdp"))
    return named or (mesh.axis_names[0],)


def data_size(mesh: Mesh) -> int:
    """Combined degree of the data axes — the N in ZeRO's 1/N shards."""
    n = 1
    for a in data_axis_names(mesh):
        n *= int(mesh.shape[a])
    return n


def fsdp_axis_name(mesh: Mesh) -> str:
    """The axis PARAMETERS shard over in ZeRO-3/FSDP: the ``fsdp`` axis when
    the mesh names one, else the last data axis (pure-dp meshes double their
    data axis as the parameter-shard axis — plain single-level FSDP)."""
    return "fsdp" if "fsdp" in mesh.axis_names else data_axis_names(mesh)[-1]


def fsdp_size(mesh: Mesh) -> int:
    return int(mesh.shape[fsdp_axis_name(mesh)])


def get_default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = data_parallel_mesh()
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]):
    global _default_mesh
    _default_mesh = mesh


def force_virtual_cpu_devices(n_devices: int) -> int:
    """Best-effort switch to an ``n_devices`` virtual CPU pod
    (``--xla_force_host_platform_device_count``) for sharding dry-runs on
    hosts without that many chips. The env route only works before jax's
    backends initialize (sitecustomize may pin ``JAX_PLATFORMS=axon`` and
    initialize at interpreter start); the config route flips an
    already-initialized process to cpu. Returns the usable device count —
    callers must clamp their mesh to it."""
    import os
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return min(n_devices, len(jax.devices()))
