"""Ring attention — sequence/context parallelism over the ICI ring.

The long-context mandate (SURVEY.md §5): the reference's only sequence-scaling tools
were bucketing and fused RNNs; a TPU-native framework must scale *attention* context
across chips. Ring attention shards the sequence over a mesh axis (``sp``): each
device holds Q/K/V for its chunk; K/V chunks rotate around the ring via ``ppermute``
(XLA lowers this to neighbor RDMA over ICI) while each device accumulates blockwise
online-softmax statistics against its resident Q — full attention over N·T context
with per-device memory O(T) and perfectly overlapped compute/communication.

Math: per ring step s, device r attends its Q block to the K/V block originally from
device (r - s) mod n, maintaining (m, l, o) flash accumulators; causal masking uses
global chunk offsets.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, get_default_mesh

__all__ = ["ring_attention_inner", "ring_self_attention"]

_NEG_INF = -1e30


def _merge_chunks(o_a, lse_a, o_b, lse_b):
    """Combine two normalized partial-attention results via their lse
    (exact blockwise-softmax composition). The _NEG_INF sentinel keeps
    fully-masked chunks at weight ~0 without producing NaNs."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    denom = jnp.maximum(wa + wb, 1e-30)
    o = (wa[..., None] * o_a + wb[..., None] * o_b) / denom[..., None]
    return o, m + jnp.log(denom)


def ring_attention_inner(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """Call INSIDE shard_map: q,k,v are the per-device sequence chunks (B,H,t,D).

    Rotates K/V with ``lax.ppermute`` (ICI neighbor exchange) n-1 times; each
    resident chunk is attended by ``ops.attention.flash_chunk`` — the Pallas
    kernel on TPU at eligible shapes — and partial results compose by their
    log-sum-exp (``_merge_chunks``), the exact blockwise-softmax identity.
    Causal masking: the diagonal chunk runs the kernel's causal mode, chunks
    entirely below the diagonal run dense, chunks above contribute weight 0
    (their lse is forced to the -inf sentinel).
    """
    from ..ops.attention import flash_chunk

    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    t = q.shape[2]
    d = q.shape[3]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    q_offset = r * t
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def offdiag_attend(s, k_cur, v_cur):
        """Attend a rotated (never-diagonal) chunk: s in [1, n-1]."""
        kf = k_cur.astype(jnp.float32)
        vf = v_cur.astype(jnp.float32)
        if not causal:
            return flash_chunk(qf, kf, vf, False, sc)
        k_offset = ((r - s) % n) * t

        def below(_):
            return flash_chunk(qf, kf, vf, False, sc)

        def above(_):
            # fully masked: contribute weight 0 WITHOUT paying the kernel
            return (jnp.zeros(qf.shape, jnp.float32),
                    jnp.full(qf.shape[:3], _NEG_INF, jnp.float32))

        return lax.cond(k_offset > q_offset, above, below, None)

    def step(s, carry):
        k_cur, v_cur, o_acc, lse_acc = carry
        # attend the resident chunk while PREFETCHING the next over ICI —
        # the two are data-independent, so XLA overlaps the ppermute RDMA
        # with the flash kernel (the ring's latency-hiding property)
        o_i, lse_i = offdiag_attend(s, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        o_acc, lse_acc = _merge_chunks(o_acc, lse_acc, o_i, lse_i)
        return k_nxt, v_nxt, o_acc, lse_acc

    # step 0 is ALWAYS the diagonal chunk (src == r) — statically known, so
    # the causal kernel call lives outside the loop; the first rotation is
    # issued alongside it (independent ops), the loop attends+prefetches
    # chunks 1..n-2, and the last chunk attends with no trailing rotation
    # (n-1 rotations total, same as the ring requires)
    o_acc, lse_acc = flash_chunk(qf, k.astype(jnp.float32),
                                 v.astype(jnp.float32), causal, sc)
    if n > 1:
        k_cur = lax.ppermute(k, axis_name, perm)
        v_cur = lax.ppermute(v, axis_name, perm)
        k_cur, v_cur, o_acc, lse_acc = lax.fori_loop(
            1, n - 1, step, (k_cur, v_cur, o_acc, lse_acc))
        o_i, lse_i = offdiag_attend(n - 1, k_cur, v_cur)
        o_acc, lse_acc = _merge_chunks(o_acc, lse_acc, o_i, lse_i)
    return o_acc.astype(q.dtype)


def sharded_attention_entry(inner, q, k, v, mesh: Optional[Mesh],
                            axis_name: str, causal: bool,
                            scale: Optional[float]):
    """Shared user-level plumbing for every sequence-parallel attention mode
    (ring here, all-to-all in ``parallel.ulysses``): NDArray unwrap, mesh /
    axis-name fallback, the T-sharded shard_map, and the one tape node that
    lets gradients flow to the q/k/v handles."""
    from ..ndarray.ndarray import NDArray
    wrap = isinstance(q, NDArray)
    handles = (q, k, v) if wrap else ()
    if wrap:
        q, k, v = q.data, k.data, v.data
    mesh = mesh or get_default_mesh()
    if axis_name not in mesh.axis_names:
        axis_name = mesh.axis_names[0]
    spec = P(None, None, axis_name, None)

    from .collectives import shard_map_compat
    fn = shard_map_compat(
        partial(inner, axis_name=axis_name, causal=causal, scale=scale),
        mesh, (spec, spec, spec), spec)
    out = fn(q, k, v)
    if not wrap:
        return out
    result = NDArray(out)
    from .. import autograd
    if autograd.is_recording():
        # one tape node so grads flow to q/k/v handles (matches registry invoke)
        autograd.record_custom_node(lambda q_, k_, v_: fn(q_, k_, v_),
                                    list(handles), [result])
    return result


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                        axis_name: str = "sp", causal: bool = False,
                        scale: Optional[float] = None):
    """User-level entry: full (B,H,T,D) arrays, sequence sharded over ``axis_name``.

    Shards T over the mesh axis, runs the ring, returns the full output (sharded the
    same way — composable with dp over another axis).
    """
    return sharded_attention_entry(ring_attention_inner, q, k, v, mesh,
                                   axis_name, causal, scale)
