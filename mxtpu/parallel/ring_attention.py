"""Ring attention — sequence/context parallelism over the ICI ring.

The long-context mandate (SURVEY.md §5): the reference's only sequence-scaling tools
were bucketing and fused RNNs; a TPU-native framework must scale *attention* context
across chips. Ring attention shards the sequence over a mesh axis (``sp``): each
device holds Q/K/V for its chunk; K/V chunks rotate around the ring via ``ppermute``
(XLA lowers this to neighbor RDMA over ICI) while each device accumulates blockwise
online-softmax statistics against its resident Q — full attention over N·T context
with per-device memory O(T) and perfectly overlapped compute/communication.

Math: per ring step s, device r attends its Q block to the K/V block originally from
device (r - s) mod n, maintaining (m, l, o) flash accumulators; causal masking uses
global chunk offsets.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, get_default_mesh

__all__ = ["ring_attention_inner", "ring_self_attention"]

_NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, scale, q_offset, k_offset, causal):
    """Accumulate one K/V block into the flash (m, l, o) stats.

    q: (B,H,Tq,D); k,v: (B,H,Tk,D); m,l: (B,H,Tq,1); o: (B,H,Tq,D).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        rows = q_offset + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        cols = k_offset + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = corr * l + jnp.sum(p, axis=-1, keepdims=True)
    o_new = corr * o + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention_inner(q, k, v, axis_name: str, causal: bool = False,
                         scale: Optional[float] = None):
    """Call INSIDE shard_map: q,k,v are the per-device sequence chunks (B,H,t,D).

    Rotates K/V with ``lax.ppermute`` (ICI neighbor exchange) n-1 times; the next
    chunk's transfer overlaps the current chunk's attention automatically (XLA
    schedules the ppermute DMA concurrently with the einsums).
    """
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    t = q.shape[2]
    d = q.shape[3]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    q_offset = r * t

    m = jnp.full(q.shape[:3] + (1,), _NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    o = jnp.zeros(q.shape, jnp.float32)
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(s, k_cur, v_cur, m, l, o):
        # K/V currently resident came from device (r - s) mod n
        src = (r - s) % n
        k_offset = src * t
        return _block_attend(qf, k_cur.astype(jnp.float32),
                             v_cur.astype(jnp.float32), m, l, o, sc,
                             q_offset, k_offset, causal)

    def step(s, carry):
        k_cur, v_cur, m, l, o = carry
        m, l, o = attend(s, k_cur, v_cur, m, l, o)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, o

    # n-1 attend+rotate steps, then a final attend — the last rotation would only
    # return chunks to their owners, so skipping it saves one full K/V RDMA per call
    k_cur, v_cur, m, l, o = lax.fori_loop(0, n - 1, step, (k, v, m, l, o))
    m, l, o = attend(n - 1, k_cur, v_cur, m, l, o)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                        axis_name: str = "sp", causal: bool = False,
                        scale: Optional[float] = None):
    """User-level entry: full (B,H,T,D) arrays, sequence sharded over ``axis_name``.

    Shards T over the mesh axis, runs the ring, returns the full output (sharded the
    same way — composable with dp over another axis).
    """
    from ..ndarray.ndarray import NDArray
    wrap = isinstance(q, NDArray)
    handles = (q, k, v) if wrap else ()
    if wrap:
        q, k, v = q.data, k.data, v.data
    mesh = mesh or get_default_mesh()
    if axis_name not in mesh.axis_names:
        axis_name = mesh.axis_names[0]
    spec = P(None, None, axis_name, None)

    fn = jax.shard_map(
        partial(ring_attention_inner, axis_name=axis_name, causal=causal,
                scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    out = fn(q, k, v)
    if not wrap:
        return out
    result = NDArray(out)
    from .. import autograd
    if autograd.is_recording():
        # one tape node so grads flow to q/k/v handles (matches registry invoke)
        autograd.record_custom_node(lambda q_, k_, v_: fn(q_, k_, v_),
                                    list(handles), [result])
    return result
