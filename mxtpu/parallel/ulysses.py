"""All-to-all sequence parallelism (DeepSpeed-Ulysses style) — the second
long-context scaling mode next to ring attention (SURVEY.md §5).

Where ring attention keeps Q resident and ROTATES K/V around the ICI ring
(n-1 neighbor hops, per-device memory O(T/n)), the all-to-all formulation
RESHUFFLES the parallel axis: sequence-sharded activations (B, H, T/n, D)
become head-sharded (B, H/n, T, D) through one ``lax.all_to_all``, every
device then runs ordinary full-sequence attention over its head group (any
kernel — the Pallas flash kernel here), and a second all_to_all restores
sequence sharding. Two collectives total regardless of sequence length, at
the cost of each device briefly holding the FULL sequence for H/n heads —
the right trade when heads ≥ devices and T is long but fits (the Ulysses
paper's regime); ring wins when even one head's full T doesn't fit.

Composable with dp/tp over other mesh axes exactly like ring attention.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .collectives import all_to_all_array
from .mesh import Mesh, get_default_mesh

__all__ = ["ulysses_attention_inner", "ulysses_self_attention"]


def ulysses_attention_inner(q, k, v, axis_name: str, causal: bool = False,
                            scale: Optional[float] = None):
    """Call INSIDE shard_map: q,k,v are sequence-sharded chunks (B, H, t, D)
    with H divisible by the axis size. all_to_all swaps seq-sharding for
    head-sharding, a single full-attention kernel runs per head group, and
    the inverse all_to_all restores (B, H, t, D)."""
    from ..ops.attention import flash_chunk

    n = lax.psum(1, axis_name)
    B, H, t, D = q.shape
    if H % n != 0:
        raise ValueError(f"ulysses: num_heads {H} must be divisible by the "
                         f"{axis_name!r} axis size {n} (use ring attention "
                         f"for head-scarce models)")

    def seq_to_heads(x):
        # (B, H, t, D) -> (B, H/n, n*t, D): split heads across the axis,
        # concatenate the sequence chunks
        return all_to_all_array(x, axis_name=axis_name, split_axis=1,
                                concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return all_to_all_array(x, axis_name=axis_name, split_axis=2,
                                concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    out, _lse = flash_chunk(qh, kh, vh, causal, s)
    return heads_to_seq(out)


def ulysses_self_attention(q, k, v, mesh: Optional[Mesh] = None,
                           axis_name: str = "sp", causal: bool = False,
                           scale: Optional[float] = None):
    """User-level entry mirroring ``ring_self_attention``: full (B,H,T,D)
    arrays, sequence sharded over ``axis_name``; returns the output sharded
    the same way. Records one tape node when autograd is live."""
    from .ring_attention import sharded_attention_entry
    return sharded_attention_entry(ulysses_attention_inner, q, k, v, mesh,
                                   axis_name, causal, scale)
