"""ZeRO-1 sharded-optimizer data parallelism — the TPU-native re-imagining of the
reference's KVStore server sharding (SURVEY §1 layer 6, ``include/mxnet/kvstore.h``):
ps-lite never holds the full optimizer state on one worker — keys are sharded across
servers, the update runs on the shard owner, and workers pull back only what they
need. Here the same ownership split is expressed in ONE fused XLA program:

* gradients are flattened into a small number of dtype-homogeneous **buckets**
  (``MXTPU_ZERO_BUCKET_MB``, default 32), each padded to a multiple of the dp
  degree;
* every bucket is constrained to ``PartitionSpec(dp)`` right after the backward —
  GSPMD converts the pending gradient reduction into a **reduce-scatter** (the
  partial-sum → sharded-consumer optimization), so each device receives only its
  1/N shard of the summed gradient (MULTICHIP_r05: reduce_scatter 64 MB = 464 ms
  vs allreduce 1117 ms);
* optimizer slots live ONLY as dp-sharded flat buckets (1/N of the state bytes per
  device, ``NamedSharding`` so checkpoint capture/restore keeps working), and the
  elementwise update runs on the shard;
* the updated shard is constrained back to replicated — one **all-gather** per
  bucket rebuilds the full parameters the next forward consumes.

Because everything happens inside the jitted step, XLA schedules the per-bucket
collectives against the remaining backward/update compute (the reference's
push/pull priority-overlap trick becomes latency hiding for free) instead of
serializing one monolithic all-reduce at the step boundary.

Eligibility: the optimizer must be **elementwise** (``Optimizer.elementwise``) —
bucket concatenation must not change the math (SGD/NAG/Adam/RMSProp/…); norm-based
(LBSGD) and noise-injecting (SGLD) optimizers fall back to the replicated path.
The mesh must be SINGLE-axis (pure dp): on multi-axis meshes this jax version's
partitioner mis-reduces concatenations of partial-sum gradients (an extra
reduction over the idle axis — verified on a (dp, tp) mesh in every constraint
formulation), so ``DataParallelTrainer``/``StepExecutor`` keep the replicated
update there.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh

__all__ = ["zero_enabled", "zero_bucket_bytes", "supports_zero", "ZeroLayout",
           "build_zero_update", "init_zero_states", "comm_dtype_of"]


def zero_enabled() -> bool:
    """Opt-out env: ``MXTPU_ZERO=0`` restores the replicated-psum path."""
    return os.environ.get("MXTPU_ZERO", "1") != "0"


def zero_bucket_bytes() -> int:
    """Bucket size cap (``MXTPU_ZERO_BUCKET_MB``, default 32 MB): small enough
    that per-bucket collectives interleave with backward compute, large enough
    to amortize collective launch latency."""
    try:
        mb = float(os.environ.get("MXTPU_ZERO_BUCKET_MB", "32"))
    except ValueError:
        mb = 32.0
    return max(1, int(mb * (1 << 20)))


def supports_zero(opt) -> bool:
    """An optimizer qualifies when its update math is elementwise (bucketing
    params into one flat array is then exact) and it uses the standard
    ``_kernel`` protocol (no custom ``update`` override like SGLD's)."""
    from ..optimizer import Optimizer
    return (getattr(opt, "elementwise", False)
            and type(opt).update is Optimizer.update
            and not getattr(opt, "multi_precision", False))


def comm_dtype_of(compression_params: Optional[dict]):
    """Comm-payload dtype selected by ``KVStore.set_gradient_compression``:
    ``fp16``/``bf16`` lower the bucket payload with an error-feedback residual;
    ``2bit`` keeps the reference's sign-threshold semantics. ``None`` → exact."""
    if not compression_params:
        return None
    kind = compression_params.get("type", "2bit")
    table = {"fp16": jnp.float16, "bf16": jnp.bfloat16, "2bit": "2bit"}
    if kind not in table:
        raise ValueError(
            f"unknown gradient compression type {kind!r}; supported kinds: "
            f"{sorted(table)} (reference gradient_compression.h ships 2bit; "
            "fp16/bf16 lower the comm payload dtype with an error-feedback "
            "residual)")
    return table[kind]


class ZeroBucket:
    """One dtype/lr-mult/wd-mult-homogeneous gradient bucket."""

    __slots__ = ("indices", "sizes", "shapes", "dtype", "lr_mult", "wd_mult",
                 "unpadded", "padded")

    def __init__(self, dtype, lr_mult: float, wd_mult: float):
        self.indices: List[int] = []
        self.sizes: List[int] = []
        self.shapes: List[tuple] = []
        self.dtype = dtype
        self.lr_mult = float(lr_mult)
        self.wd_mult = float(wd_mult)
        self.unpadded = 0
        self.padded = 0

    @property
    def nbytes(self) -> int:
        return self.unpadded * np.dtype(self.dtype).itemsize

    def describe(self) -> dict:
        return {"indices": list(self.indices), "sizes": list(self.sizes),
                "dtype": str(np.dtype(self.dtype)), "unpadded": self.unpadded,
                "lr_mult": self.lr_mult, "wd_mult": self.wd_mult}


class ZeroLayout:
    """Deterministic bucket layout over a parameter list.

    Grouping (by dtype and per-param lr/wd multiplier, chunked at
    ``bucket_bytes``) is independent of the dp degree — only the per-bucket
    PADDING depends on N — so a checkpointed state restores onto a different
    dp size by stripping the old pad and re-padding (``adopt_states``).
    """

    def __init__(self, params: Sequence, lr_mults: Sequence[float],
                 wd_mults: Sequence[float], dp: int,
                 eligible: Optional[Sequence[bool]] = None,
                 bucket_bytes: Optional[int] = None):
        self.dp = max(1, int(dp))
        bucket_bytes = bucket_bytes or zero_bucket_bytes()
        self.buckets: List[ZeroBucket] = []
        self.passthrough: List[int] = []
        open_buckets: Dict[tuple, ZeroBucket] = {}
        for i, w in enumerate(params):
            if eligible is not None and not eligible[i]:
                self.passthrough.append(i)
                continue
            dt = np.dtype(str(w.dtype))
            key = (str(dt), float(lr_mults[i]), float(wd_mults[i]))
            b = open_buckets.get(key)
            if b is None or b.nbytes >= bucket_bytes:
                b = ZeroBucket(dt, lr_mults[i], wd_mults[i])
                open_buckets[key] = b
                self.buckets.append(b)
            n = int(np.prod(w.shape)) if len(w.shape) else 1
            b.indices.append(i)
            b.sizes.append(n)
            b.shapes.append(tuple(w.shape))
            b.unpadded += n
        for b in self.buckets:
            b.padded = -(-b.unpadded // self.dp) * self.dp

    # -- identity ----------------------------------------------------------
    def fingerprint(self) -> tuple:
        return (self.dp, tuple(self.passthrough),
                tuple((tuple(b.indices), b.unpadded, str(b.dtype),
                       b.lr_mult, b.wd_mult) for b in self.buckets))

    def describe(self) -> dict:
        """JSON-able layout record for checkpoint meta."""
        return {"dp": self.dp, "passthrough": list(self.passthrough),
                "buckets": [b.describe() for b in self.buckets]}

    def compatible_with(self, desc: dict) -> bool:
        """True when ``desc`` (a saved ``describe()``) has the same grouping —
        dp may differ (padding is re-derived), bucket membership may not."""
        if not desc:
            return False
        saved = desc.get("buckets", [])
        if len(saved) != len(self.buckets):
            return False
        for s, b in zip(saved, self.buckets):
            if (s.get("indices") != list(b.indices)
                    or s.get("sizes") != list(b.sizes)
                    or np.dtype(s.get("dtype")) != b.dtype):
                return False
        return True

    # -- accounting --------------------------------------------------------
    def step_comm(self) -> dict:
        """Analytic per-device comm bytes for ONE step: ring reduce-scatter
        moves (N-1)/N of each bucket per device, the parameter all-gather the
        same — vs 2·(N-1)/N of the FULL gradient for a ring all-reduce."""
        n = self.dp
        frac = (n - 1) / n if n > 1 else 0.0
        total = sum(b.nbytes for b in self.buckets)
        return {
            "bytes_reduced": int(total * frac),
            "bytes_gathered": int(total * frac),
            "bucket_count": len(self.buckets),
            "shard_bytes": int(sum(-(-b.nbytes // n) for b in self.buckets)),
            "dp": n,
        }

    def state_bytes_per_device(self, states: Sequence[Tuple]) -> int:
        """Actual optimizer-slot bytes resident per device (sharded slots
        count 1/N; scalar/replicated slots count fully)."""
        total = 0
        for b, st in zip(self.buckets, states):
            for s in st:
                nb = int(np.dtype(str(s.dtype)).itemsize
                         * int(np.prod(s.shape))) if hasattr(s, "shape") else 0
                total += nb // self.dp if getattr(s, "shape", ()) == \
                    (b.padded,) else nb
        return total

    # -- state shard/unshard ----------------------------------------------
    def shard_spec(self, mesh: Mesh):
        # dp=1: P('dp') and P() are the same layout, but XLA normalizes
        # outputs to P() — use P() up front so the step signature (which
        # includes shardings) stays stable across steps (no retrace)
        if self.dp == 1:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(mesh.axis_names[0]))

    def repl_spec(self, mesh: Mesh):
        return NamedSharding(mesh, P())

    def adopt_states(self, saved_arrays: Dict[str, np.ndarray],
                     saved_desc: dict, mesh: Mesh):
        """Re-place checkpointed bucket states onto THIS layout's mesh/dp:
        strip the saved padding (saved dp may differ), re-pad to the current
        multiple, place sharded. Returns ``(states, residuals)`` or ``None``
        when the saved layout is incompatible (caller starts fresh)."""
        if not self.compatible_with(saved_desc):
            return None
        from .data_parallel import _place
        shard = self.shard_spec(mesh)
        repl = self.repl_spec(mesh)
        states: List[Tuple] = []
        residuals: List[Any] = []
        for bi, b in enumerate(self.buckets):
            st = []
            j = 0
            while f"zopt:{bi}:{j}" in saved_arrays:
                raw = np.asarray(saved_arrays[f"zopt:{bi}:{j}"])
                if raw.ndim == 1 and raw.shape[0] >= b.unpadded:
                    flat = np.zeros((b.padded,), raw.dtype)
                    flat[:b.unpadded] = raw[:b.unpadded]
                    st.append(_place(flat, shard))
                else:                       # scalar/replicated slot
                    st.append(_place(raw, repl))
                j += 1
            states.append(tuple(st))
            rk = f"zres:{bi}"
            if rk in saved_arrays:
                raw = np.asarray(saved_arrays[rk])
                flat = np.zeros((b.padded,), raw.dtype)
                flat[:min(b.unpadded, raw.shape[0])] = raw[:b.unpadded]
                residuals.append(_place(flat, shard))
            else:
                residuals.append(None)
        return states, residuals


# ---------------------------------------------------------------------------
# state init
# ---------------------------------------------------------------------------


def _bucket_weight(layout: ZeroLayout, b: ZeroBucket, param_raws):
    flats = [jnp.ravel(param_raws[i]).astype(b.dtype) for i in b.indices]
    if b.padded > b.unpadded:
        flats.append(jnp.zeros((b.padded - b.unpadded,), b.dtype))
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0]


def init_zero_states(opt, layout: ZeroLayout, param_raws, mesh: Mesh,
                     with_residual: bool = False):
    """Create per-bucket optimizer slots, placed dp-sharded (1/N resident per
    device). Slot shapes follow ``create_state`` on the flat bucket "weight"
    (so DCASGD's prev-weight copy, Nadam's scalar schedule, … all work);
    bucket-shaped slots shard over dp, scalar slots stay replicated."""
    from .data_parallel import _place
    from ..ndarray.ndarray import NDArray
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    states: List[Tuple] = []
    residuals: List[Any] = []
    for bi, b in enumerate(layout.buckets):
        w_full = _bucket_weight(layout, b, param_raws)
        st = opt.create_state(("zero", bi), NDArray(w_full))
        placed = tuple(
            _place(s, shard if getattr(s, "shape", None) == (b.padded,)
                   else repl) for s in st)
        states.append(placed)
        residuals.append(_place(jnp.zeros((b.padded,), jnp.float32), shard)
                         if with_residual else None)
    return states, residuals


def state_shardings(layout: ZeroLayout, states, mesh: Mesh):
    """Matching NamedSharding pytree for jit in/out_shardings."""
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    return [tuple(shard if getattr(s, "shape", None) == (b.padded,) else repl
                  for s in st)
            for b, st in zip(layout.buckets, states)]


# ---------------------------------------------------------------------------
# the traced update
# ---------------------------------------------------------------------------


def build_zero_update(opt, layout: ZeroLayout, mesh: Mesh,
                      comm_dtype=None, compression_params: Optional[dict] = None):
    """One traceable function applying ``opt`` to every bucketed parameter
    through the reduce-scatter → shard-update → all-gather dataflow.

    Returns ``zero_update(params, grads, states, residuals, lr, wd, rescale,
    clip, t) -> (new_params, new_states, new_residuals)``. ``params`` and
    ``grads`` are the full per-param lists; passthrough (non-bucketed, e.g.
    tensor-parallel) parameters are NOT updated here — callers compose with
    ``build_update_all`` for those.

    The two ``with_sharding_constraint`` calls are the whole trick: the first
    lands on the gradient while its cross-dp reduction is still pending, so
    GSPMD materializes it as a reduce-scatter; the second forces the updated
    shard back to replicated, an all-gather. Per-bucket, so XLA interleaves
    the collectives with the rest of the backward/update instead of fencing
    the step on one monolithic all-reduce.
    """
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    clipped = opt.clip_gradient is not None
    thr = float((compression_params or {}).get("threshold", 0.5))

    def zero_update(params, grads, states, residuals, lr, wd, rescale, clip, t):
        new_params = list(params)
        new_states = []
        new_residuals = []
        for bi, b in enumerate(layout.buckets):
            dt = jnp.dtype(str(b.dtype))
            flats = [jnp.ravel(grads[i]) for i in b.indices]
            if b.padded > b.unpadded:
                flats.append(jnp.zeros((b.padded - b.unpadded,), flats[0].dtype))
            g_full = jnp.concatenate(flats) if len(flats) > 1 else flats[0]
            # pending dp-reduction + sharded consumer → GSPMD reduce-scatter
            g_shard = jax.lax.with_sharding_constraint(
                g_full.astype(dt), shard)
            w_full = _bucket_weight(layout, b, params)
            w_shard = jax.lax.with_sharding_constraint(w_full, shard)
            gg = opt._preprocess_grad(g_shard, rescale.astype(dt),
                                      clip.astype(dt) if clipped else None)
            res = residuals[bi]
            if comm_dtype is not None:
                # error-feedback payload lowering on the owned shard: the
                # quantization error re-enters next step's gradient, so the
                # compressed run converges to the uncompressed fixpoint
                # (gradient_compression.h:37 semantics at ZeRO granularity)
                e = gg.astype(jnp.float32) + res
                if comm_dtype == "2bit":
                    q = (jnp.where(e >= thr, thr, 0.0)
                         + jnp.where(e <= -thr, -thr, 0.0))
                else:
                    q = e.astype(comm_dtype).astype(jnp.float32)
                res = jax.lax.with_sharding_constraint(e - q, shard)
                gg = q.astype(dt)
            out = opt._kernel(w_shard, gg, lr.astype(dt) * b.lr_mult,
                              wd.astype(dt) * b.wd_mult, t, *states[bi])
            if isinstance(out, tuple):
                new_w_shard, new_st = out[0], tuple(out[1:])
            else:
                new_w_shard, new_st = out, ()
            new_states.append(tuple(
                jax.lax.with_sharding_constraint(s, shard)
                if getattr(s, "shape", None) == (b.padded,) else s
                for s in new_st))
            new_residuals.append(res)
            # updated shard → replicated params: the all-gather
            new_w_full = jax.lax.with_sharding_constraint(new_w_shard, repl)
            off = 0
            for i, n, shp in zip(b.indices, b.sizes, b.shapes):
                new_params[i] = jax.lax.dynamic_slice_in_dim(
                    new_w_full, off, n).reshape(shp).astype(params[i].dtype)
                off += n
        return new_params, new_states, new_residuals

    return zero_update
