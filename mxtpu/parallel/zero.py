"""ZeRO sharded data parallelism — the TPU-native re-imagining of the
reference's KVStore server sharding (SURVEY §1 layer 6, ``include/mxnet/kvstore.h``):
ps-lite never holds the full optimizer state on one worker — keys are sharded across
servers, the update runs on the shard owner, and workers pull back only what they
need. Here the same ownership split is expressed in ONE fused XLA program, staged
per ZeRO (Rajbhandari et al., 2020) via ``MXTPU_ZERO_STAGE`` (see
``parallel/fsdp.py``):

* gradients are flattened into a small number of dtype-homogeneous **buckets**
  (``MXTPU_ZERO_BUCKET_MB``, default 32), each param padded to a multiple of the
  data degree N and packed into an N-interleaved flat layout (device d owns the
  d-th chunk of every member param);
* each per-param gradient is constrained to the data-axis sharding right after
  the backward — GSPMD converts the pending per-axis reduction into a
  **reduce-scatter** (the partial-sum → sharded-consumer optimization)
  (MULTICHIP_r05: reduce_scatter 64 MB = 464 ms vs allreduce 1117 ms) — and the
  owned shards are packed with a ``shard_map`` local concat. The per-param
  constraint + explicit local pack is load-bearing: concatenating partial-sum
  gradients BEFORE the constraint trips a partitioner mis-reduction on
  multi-axis meshes (an extra reduction over the idle axis, verified on
  (dp, tp)), which is why PR 4 had to fall back to replicated updates there.
  Per-param resolution over named axes is exact on any mesh, so the fallback
  is gone and ZeRO composes with tensor parallelism;
* optimizer slots live ONLY as data-sharded flat buckets (1/N of the state
  bytes per device, ``NamedSharding`` so checkpoint capture/restore keeps
  working), and the elementwise update runs on the shard;
* the updated packed shard is constrained back to replicated — one
  **all-gather** per bucket — and de-interleaved with static slices into the
  full parameters the next forward consumes. At stage 3 (FSDP) shardable
  params never enter buckets at all: they stay resident 1/N on the ``fsdp``
  axis and take the per-param sharded update (``parallel/fsdp.py``).

Because everything happens inside the jitted step, XLA schedules the per-bucket
collectives against the remaining backward/update compute (the reference's
push/pull priority-overlap trick becomes latency hiding for free) instead of
serializing one monolithic all-reduce at the step boundary.

Eligibility: the optimizer must be **elementwise** (``Optimizer.elementwise``) —
bucket packing must not change the math (SGD/NAG/Adam/RMSProp/…); norm-based
(LBSGD) and noise-injecting (SGLD) optimizers fall back to the replicated path.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import Mesh, data_axis_names

__all__ = ["zero_enabled", "zero_bucket_bytes", "supports_zero", "ZeroLayout",
           "build_zero_update", "build_grad_pack", "init_zero_states",
           "state_shardings", "comm_dtype_of"]


def zero_enabled() -> bool:
    """Opt-out env: ``MXTPU_ZERO=0`` restores the replicated-psum path."""
    return os.environ.get("MXTPU_ZERO", "1") != "0"


def zero_bucket_bytes() -> int:
    """Bucket size cap (``MXTPU_ZERO_BUCKET_MB``, default 32 MB): small enough
    that per-bucket collectives interleave with backward compute, large enough
    to amortize collective launch latency."""
    try:
        mb = float(os.environ.get("MXTPU_ZERO_BUCKET_MB", "32"))
    except ValueError:
        mb = 32.0
    return max(1, int(mb * (1 << 20)))


def supports_zero(opt) -> bool:
    """An optimizer qualifies when its update math is elementwise (bucketing
    params into one flat array is then exact) and it uses the standard
    ``_kernel`` protocol (no custom ``update`` override like SGLD's)."""
    from ..optimizer import Optimizer
    return (getattr(opt, "elementwise", False)
            and type(opt).update is Optimizer.update
            and not getattr(opt, "multi_precision", False))


def comm_dtype_of(compression_params: Optional[dict]):
    """Comm-payload dtype selected by ``KVStore.set_gradient_compression``:
    ``fp16``/``bf16`` lower the bucket payload with an error-feedback residual;
    ``2bit`` keeps the reference's sign-threshold semantics. ``None`` → exact."""
    if not compression_params:
        return None
    kind = compression_params.get("type", "2bit")
    table = {"fp16": jnp.float16, "bf16": jnp.bfloat16, "2bit": "2bit"}
    if kind not in table:
        raise ValueError(
            f"unknown gradient compression type {kind!r}; supported kinds: "
            f"{sorted(table)} (reference gradient_compression.h ships 2bit; "
            "fp16/bf16 lower the comm payload dtype with an error-feedback "
            "residual)")
    return table[kind]


class ZeroBucket:
    """One dtype/lr-mult/wd-mult-homogeneous gradient bucket.

    Packed layout: every member param is padded to ``psizes[k]`` (a multiple
    of N) and the bucket is N-INTERLEAVED — viewing the flat bucket as
    ``(N, padded // N)``, row d is the concat of every param's d-th chunk.
    Device d therefore owns a contiguous slice of each param, the pack is a
    shard-local concat (no cross-device data motion), and the layout degrades
    to a plain concatenation at N = 1."""

    __slots__ = ("indices", "sizes", "psizes", "shapes", "dtype", "lr_mult",
                 "wd_mult", "unpadded", "padded")

    def __init__(self, dtype, lr_mult: float, wd_mult: float):
        self.indices: List[int] = []
        self.sizes: List[int] = []
        self.psizes: List[int] = []
        self.shapes: List[tuple] = []
        self.dtype = dtype
        self.lr_mult = float(lr_mult)
        self.wd_mult = float(wd_mult)
        self.unpadded = 0
        self.padded = 0

    @property
    def nbytes(self) -> int:
        return self.unpadded * np.dtype(self.dtype).itemsize

    def describe(self) -> dict:
        return {"indices": list(self.indices), "sizes": list(self.sizes),
                "psizes": list(self.psizes), "dtype": str(np.dtype(self.dtype)),
                "unpadded": self.unpadded,
                "lr_mult": self.lr_mult, "wd_mult": self.wd_mult}


def _pack_flat_host(flats: Sequence[np.ndarray], psizes: Sequence[int],
                    n: int) -> np.ndarray:
    """Host-side interleave: pad each flat to its psize and stack the
    per-device chunks column-wise → the packed global bucket."""
    cols = []
    for a, ps in zip(flats, psizes):
        a = np.ravel(np.asarray(a))
        flat = np.zeros((ps,), a.dtype)
        flat[:a.shape[0]] = a
        cols.append(flat.reshape(n, ps // n))
    mat = np.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
    return np.ascontiguousarray(mat.reshape(-1))


def _unpack_flat_host(packed: np.ndarray, sizes: Sequence[int],
                      psizes: Sequence[int], n: int) -> List[np.ndarray]:
    """Inverse of ``_pack_flat_host``: per-param unpadded flats."""
    packed = np.ravel(np.asarray(packed))
    mat = packed.reshape(n, packed.shape[0] // n)
    outs, off = [], 0
    for sz, ps in zip(sizes, psizes):
        step = ps // n
        outs.append(np.ascontiguousarray(
            mat[:, off:off + step].reshape(-1)[:sz]))
        off += step
    return outs


class ZeroLayout:
    """Deterministic bucket layout over a parameter list.

    Grouping (by dtype and per-param lr/wd multiplier, chunked at
    ``bucket_bytes``) is independent of the data degree — only the per-param
    PADDING (and hence the interleave) depends on N — so a checkpointed state
    restores onto a different degree by de-interleaving with the saved
    N/psizes and re-packing with the current ones (``adopt_states``).

    ``eligible`` masks params OUT of the buckets (``passthrough``): at
    stages 1/2 that is the tensor-parallel params (their grads reduce over
    the tp axis, not dp); at stage 3 it is additionally every fsdp-shardable
    param, which gets the per-param resident-sharded update instead.
    """

    def __init__(self, params: Sequence, lr_mults: Sequence[float],
                 wd_mults: Sequence[float], dp: int,
                 eligible: Optional[Sequence[bool]] = None,
                 bucket_bytes: Optional[int] = None):
        self.dp = max(1, int(dp))
        bucket_bytes = bucket_bytes or zero_bucket_bytes()
        self.buckets: List[ZeroBucket] = []
        self.passthrough: List[int] = []
        open_buckets: Dict[tuple, ZeroBucket] = {}
        for i, w in enumerate(params):
            if eligible is not None and not eligible[i]:
                self.passthrough.append(i)
                continue
            dt = np.dtype(str(w.dtype))
            key = (str(dt), float(lr_mults[i]), float(wd_mults[i]))
            b = open_buckets.get(key)
            if b is None or b.nbytes >= bucket_bytes:
                b = ZeroBucket(dt, lr_mults[i], wd_mults[i])
                open_buckets[key] = b
                self.buckets.append(b)
            n = int(np.prod(w.shape)) if len(w.shape) else 1
            b.indices.append(i)
            b.sizes.append(n)
            b.shapes.append(tuple(w.shape))
            b.unpadded += n
        for b in self.buckets:
            b.psizes = [-(-s // self.dp) * self.dp for s in b.sizes]
            b.padded = sum(b.psizes)

    # -- identity ----------------------------------------------------------
    def fingerprint(self) -> tuple:
        return (self.dp, tuple(self.passthrough),
                tuple((tuple(b.indices), b.unpadded, str(b.dtype),
                       b.lr_mult, b.wd_mult) for b in self.buckets))

    def describe(self) -> dict:
        """JSON-able layout record for checkpoint meta."""
        return {"dp": self.dp, "passthrough": list(self.passthrough),
                "buckets": [b.describe() for b in self.buckets]}

    def compatible_with(self, desc: dict) -> bool:
        """True when ``desc`` (a saved ``describe()``) has the same grouping —
        dp may differ (the interleave is re-derived from the saved psizes),
        bucket membership may not. Pre-packed-format checkpoints (no psizes
        recorded) are incompatible: their flat layout cannot be de-interleaved."""
        if not desc:
            return False
        saved = desc.get("buckets", [])
        if len(saved) != len(self.buckets):
            return False
        for s, b in zip(saved, self.buckets):
            if (s.get("indices") != list(b.indices)
                    or s.get("sizes") != list(b.sizes)
                    or not s.get("psizes")
                    or np.dtype(s.get("dtype")) != b.dtype):
                return False
        return True

    # -- accounting --------------------------------------------------------
    def step_comm(self) -> dict:
        """Analytic per-device comm bytes for ONE step: ring reduce-scatter
        moves (N-1)/N of each bucket per device, the parameter all-gather the
        same — vs 2·(N-1)/N of the FULL gradient for a ring all-reduce."""
        n = self.dp
        frac = (n - 1) / n if n > 1 else 0.0
        total = sum(b.nbytes for b in self.buckets)
        return {
            "bytes_reduced": int(total * frac),
            "bytes_gathered": int(total * frac),
            "bucket_count": len(self.buckets),
            "shard_bytes": int(sum(-(-b.nbytes // n) for b in self.buckets)),
            "dp": n,
        }

    def state_bytes_per_device(self, states: Sequence[Tuple]) -> int:
        """Actual optimizer-slot bytes resident per device (sharded slots
        count 1/N; scalar/replicated slots count fully)."""
        total = 0
        for b, st in zip(self.buckets, states):
            for s in st:
                nb = int(np.dtype(str(s.dtype)).itemsize
                         * int(np.prod(s.shape))) if hasattr(s, "shape") else 0
                total += nb // self.dp if getattr(s, "shape", ()) == \
                    (b.padded,) else nb
        return total

    # -- state shard/unshard ----------------------------------------------
    def data_spec(self, mesh: Mesh) -> P:
        """1-D PartitionSpec over every data axis of ``mesh`` (dp×fsdp)."""
        axes = data_axis_names(mesh)
        return P(axes if len(axes) > 1 else axes[0])

    def shard_spec(self, mesh: Mesh):
        # dp=1: the data spec and P() are the same layout, but XLA normalizes
        # outputs to P() — use P() up front so the step signature (which
        # includes shardings) stays stable across steps (no retrace)
        if self.dp == 1:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, self.data_spec(mesh))

    def repl_spec(self, mesh: Mesh):
        return NamedSharding(mesh, P())

    def adopt_states(self, saved_arrays: Dict[str, np.ndarray],
                     saved_desc: dict, mesh: Mesh):
        """Re-place checkpointed bucket states onto THIS layout's mesh/dp:
        de-interleave with the SAVED dp/psizes, re-pack with the current ones,
        place sharded. Returns ``(states, residuals)`` or ``None`` when the
        saved layout is incompatible (caller starts fresh)."""
        if not self.compatible_with(saved_desc):
            return None
        from .data_parallel import _place
        old_n = max(1, int(saved_desc.get("dp", 1)))
        saved_buckets = saved_desc.get("buckets", [])
        shard = self.shard_spec(mesh)
        repl = self.repl_spec(mesh)

        def repack(raw: np.ndarray, b: ZeroBucket, old_ps: List[int]):
            flats = _unpack_flat_host(raw, b.sizes, old_ps, old_n)
            return _pack_flat_host(flats, b.psizes, self.dp)

        states: List[Tuple] = []
        residuals: List[Any] = []
        for bi, b in enumerate(self.buckets):
            old_ps = [int(v) for v in saved_buckets[bi]["psizes"]]
            old_padded = sum(old_ps)
            st = []
            j = 0
            while f"zopt:{bi}:{j}" in saved_arrays:
                raw = np.asarray(saved_arrays[f"zopt:{bi}:{j}"])
                if raw.ndim == 1 and raw.shape[0] == old_padded:
                    st.append(_place(repack(raw, b, old_ps), shard))
                else:                       # scalar/replicated slot
                    st.append(_place(raw, repl))
                j += 1
            states.append(tuple(st))
            rk = f"zres:{bi}"
            if rk in saved_arrays:
                raw = np.asarray(saved_arrays[rk])
                if raw.shape[0] == old_padded:
                    residuals.append(_place(repack(raw, b, old_ps), shard))
                else:
                    residuals.append(
                        _place(np.zeros((b.padded,), raw.dtype), shard))
            else:
                residuals.append(None)
        return states, residuals


# ---------------------------------------------------------------------------
# state init
# ---------------------------------------------------------------------------


def _bucket_weight(layout: ZeroLayout, b: ZeroBucket, param_raws):
    """Packed (N-interleaved) bucket weight, traceable. Params carry no
    pending reduction, so reshape/concat are layout-only here — the
    partitioner hazard is specific to partial-sum GRADIENTS."""
    n = layout.dp
    cols = []
    for i, sz, ps in zip(b.indices, b.sizes, b.psizes):
        flat = jnp.ravel(param_raws[i]).astype(b.dtype)
        if ps > sz:
            flat = jnp.pad(flat, (0, ps - sz))
        cols.append(flat.reshape(n, ps // n))
    mat = jnp.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
    return mat.reshape(-1)


def _unpack_bucket(new_w_full, b: ZeroBucket, n: int):
    """Static-slice de-interleave of a REPLICATED packed bucket back into
    per-param flats (runs after the all-gather, no pending reductions)."""
    mat = new_w_full.reshape(n, b.padded // n)
    outs, off = [], 0
    for sz, ps in zip(b.sizes, b.psizes):
        step = ps // n
        outs.append(mat[:, off:off + step].reshape(-1)[:sz])
        off += step
    return outs


def init_zero_states(opt, layout: ZeroLayout, param_raws, mesh: Mesh,
                     with_residual: bool = False):
    """Create per-bucket optimizer slots, placed data-sharded (1/N resident
    per device). Slot shapes follow ``create_state`` on the flat bucket
    "weight" (so DCASGD's prev-weight copy, Nadam's scalar schedule, … all
    work); bucket-shaped slots shard over the data axes, scalar slots stay
    replicated."""
    from .data_parallel import _place
    from ..ndarray.ndarray import NDArray
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    states: List[Tuple] = []
    residuals: List[Any] = []
    for bi, b in enumerate(layout.buckets):
        w_full = _bucket_weight(layout, b, param_raws)
        st = opt.create_state(("zero", bi), NDArray(w_full))
        placed = tuple(
            _place(s, shard if getattr(s, "shape", None) == (b.padded,)
                   else repl) for s in st)
        states.append(placed)
        residuals.append(_place(jnp.zeros((b.padded,), jnp.float32), shard)
                         if with_residual else None)
    return states, residuals


def state_shardings(layout: ZeroLayout, states, mesh: Mesh):
    """Matching NamedSharding pytree for jit in/out_shardings."""
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    return [tuple(shard if getattr(s, "shape", None) == (b.padded,) else repl
                  for s in st)
            for b, st in zip(layout.buckets, states)]


# ---------------------------------------------------------------------------
# the traced update
# ---------------------------------------------------------------------------


def _build_bucket_pack(layout: ZeroLayout, mesh: Mesh):
    """Traceable per-bucket gradient pack: per-param pad → per-param data-axis
    sharding constraint (GSPMD resolves each pending reduction as a
    reduce-scatter over the NAMED axes — exact on any mesh) → shard_map local
    concat into the packed shard. The per-param constraint must come BEFORE
    any concatenation: concat of partial-sum grads is what the partitioner
    mis-reduces on multi-axis meshes."""
    n = layout.dp
    spec1d = layout.data_spec(mesh)
    shard = layout.shard_spec(mesh)

    def pack_bucket(b: ZeroBucket, grads, dt):
        flats = []
        for i, sz, ps in zip(b.indices, b.sizes, b.psizes):
            f = jnp.ravel(grads[i])
            if ps > sz:
                f = jnp.pad(f, (0, ps - sz))
            f = f.astype(dt)
            if n > 1:
                f = jax.lax.with_sharding_constraint(f, shard)
            flats.append(f)
        if len(flats) == 1:
            return flats[0]
        if n == 1:
            return jnp.concatenate(flats)
        from .collectives import shard_map_compat
        local_concat = shard_map_compat(
            lambda *locs: jnp.concatenate(locs), mesh,
            in_specs=tuple(spec1d for _ in flats),
            out_specs=spec1d, check=False)
        return local_concat(*flats)

    return pack_bucket


def build_grad_pack(layout: ZeroLayout, mesh: Mesh):
    """Traceable ``pack_grads(grads) -> [packed f32 bucket shards]`` — the
    ZeRO-2 entry point: micro-batch loops reduce-scatter each micro-gradient
    into the 1/N packed shard and accumulate THAT, so accumulation memory is
    the bucket shard, never the replicated gradient."""
    pack_bucket = _build_bucket_pack(layout, mesh)

    def pack_grads(grads):
        return [pack_bucket(b, grads, jnp.float32) for b in layout.buckets]

    return pack_grads


def build_zero_update(opt, layout: ZeroLayout, mesh: Mesh,
                      comm_dtype=None, compression_params: Optional[dict] = None):
    """One traceable function applying ``opt`` to every bucketed parameter
    through the reduce-scatter → shard-update → all-gather dataflow.

    Returns ``zero_update(params, grads, states, residuals, lr, wd, rescale,
    clip, t, packed_grads=None) -> (new_params, new_states, new_residuals)``.
    ``params`` and ``grads`` are the full per-param lists; passthrough
    (non-bucketed: tensor-parallel, or fsdp-resident at stage 3) parameters
    are NOT updated here — callers compose with ``build_update_all`` for
    those. ``packed_grads`` (from ``build_grad_pack``, stage 2) bypasses the
    gradient pack when the caller already holds reduce-scattered shards.

    The sharding constraints are the whole trick: the per-param constraint
    lands on each gradient while its cross-data-axis reduction is still
    pending, so GSPMD materializes a per-axis reduce-scatter; the final
    constraint forces the updated packed shard back to replicated, an
    all-gather. Per-bucket, so XLA interleaves the collectives with the rest
    of the backward/update instead of fencing the step on one monolithic
    all-reduce.
    """
    shard = layout.shard_spec(mesh)
    repl = layout.repl_spec(mesh)
    n = layout.dp
    pack_bucket = _build_bucket_pack(layout, mesh)
    clipped = opt.clip_gradient is not None
    thr = float((compression_params or {}).get("threshold", 0.5))

    def zero_update(params, grads, states, residuals, lr, wd, rescale, clip, t,
                    packed_grads=None):
        new_params = list(params)
        new_states = []
        new_residuals = []
        for bi, b in enumerate(layout.buckets):
            dt = jnp.dtype(str(b.dtype))
            if packed_grads is not None:
                g_shard = packed_grads[bi].astype(dt)
            else:
                g_shard = pack_bucket(b, grads, dt)
            w_full = _bucket_weight(layout, b, params)
            w_shard = jax.lax.with_sharding_constraint(w_full, shard)
            gg = opt._preprocess_grad(g_shard, rescale.astype(dt),
                                      clip.astype(dt) if clipped else None)
            res = residuals[bi]
            if comm_dtype is not None:
                # error-feedback payload lowering on the owned shard: the
                # quantization error re-enters next step's gradient, so the
                # compressed run converges to the uncompressed fixpoint
                # (gradient_compression.h:37 semantics at ZeRO granularity)
                e = gg.astype(jnp.float32) + res
                if comm_dtype == "2bit":
                    q = (jnp.where(e >= thr, thr, 0.0)
                         + jnp.where(e <= -thr, -thr, 0.0))
                else:
                    q = e.astype(comm_dtype).astype(jnp.float32)
                res = jax.lax.with_sharding_constraint(e - q, shard)
                gg = q.astype(dt)
            out = opt._kernel(w_shard, gg, lr.astype(dt) * b.lr_mult,
                              wd.astype(dt) * b.wd_mult, t, *states[bi])
            if isinstance(out, tuple):
                new_w_shard, new_st = out[0], tuple(out[1:])
            else:
                new_w_shard, new_st = out, ()
            new_states.append(tuple(
                jax.lax.with_sharding_constraint(s, shard)
                if getattr(s, "shape", None) == (b.padded,) else s
                for s in new_st))
            new_residuals.append(res)
            # updated packed shard → replicated: the all-gather; then a
            # static-slice de-interleave rebuilds each full parameter
            new_w_full = jax.lax.with_sharding_constraint(new_w_shard, repl)
            for i, flat in zip(b.indices, _unpack_bucket(new_w_full, b, n)):
                new_params[i] = flat.reshape(
                    params[i].shape).astype(params[i].dtype)
        return new_params, new_states, new_residuals

    return zero_update
